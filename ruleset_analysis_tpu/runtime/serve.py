"""Always-on streaming service mode: live ingest, windowed registers,
hot ruleset reload.

The batch drivers answer "was this rule used *ever* in this corpus"; a
production deletion decision needs "was it used in the last 24h/7d" on
*live* traffic (ROADMAP item 1).  This module turns the pipeline into a
long-running service with three pillars:

1. **Listener tier** (hostside/listener.py): UDP/TCP syslog sockets and
   a rotating-file tailer feed a bounded queue with explicit drop
   accounting.  The serve loop forms batches with the batch drivers'
   exact boundary rules (stream.LineBatcher) and steps them through the
   same jitted device programs.

2. **Windowed registers.**  Time is cut into windows (wall-clock cadence
   or a deterministic line count); each window accumulates into a FRESH
   register state, and at rotation the window's registers are pulled to
   host and pushed into a ring of N mergeable epochs.  Because every
   register obeys the merge laws the collective step already relies on
   (``parallel/step.py::_merge_tail``: psum = add for counts/CMS, pmax =
   max for HLL), merging K epochs is bit-identical to a single replay
   over the concatenated traffic — so "unused in the last K windows" is
   one cheap host-side merge, not a re-run (tests/test_serve.py pins the
   law).  The ring — epochs, counters, per-window trackers, quarantine —
   rides the existing checkpoint plane (CRC'd manifests, atomic pointer
   publish), so a restarted service resumes with its history intact.

3. **Publication + hot reload.**  Every rotation publishes the window
   report, the cumulative report, and a ``diff-reports``-machinery diff
   against the previous window to the serve directory and a minimal
   loopback HTTP JSON endpoint (/report, /health, /metrics).  A SIGHUP
   or a watched ruleset-file change re-packs the rule tensor mid-stream:
   a key-space **migration map** (rule identity = firewall/ACL/text, so
   counters survive renumbering) rewrites the live state AND every ring
   epoch into the new key space; keys with hits that map nowhere land in
   an explicit **quarantine bucket** — reported, never dropped.  A
   reload that fails at any point (including the ``reload.midbatch``
   fault site) leaves the old tensor and counters untouched.

Drop invariant: any window that overlaps a dropped line (queue overflow,
forced ``listener.drop`` fault, dead listener) is stamped with a typed
``WindowIncomplete`` marker (``totals.window.incomplete``) in every
report that includes it — never silently reported as zero-hit.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque

import numpy as np

from ..config import AnalysisConfig, AutoscaleConfig, ServeConfig
from ..errors import AnalysisError, FeedWorkerError, StallError
from ..hostside import pack as pack_mod
from ..hostside.listener import LineQueue, ListenerSet
from ..models import pipeline
from ..ops.topk import TopKTracker
from . import checkpoint as ckpt
from . import devprof, epochstore, faults, flightrec, obs, retrypolicy
from .metrics import (
    LatencyHistogram,
    SloBurnEngine,
    SloPolicy,
    build_info,
    render_build_info_prom,
    window_slo_stats,
)
from .wal import DEFAULT_TENANT, LineageLog, WriteAheadLog
from .autoscale import (
    PolicyEngine,
    render_prom,
    render_prom_labeled,
    world_ladder,
)
from .report import diff_report_objs, seal_lineage, trend_events

def merge_register_arrays(items: list[dict[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Merge K window register images under the _merge_tail laws.

    Bit-identical to accumulating the concatenated traffic into one
    state: 64-bit counts add exactly (the device's add64 carries), CMS
    planes add mod 2^32 (psum wraps identically), HLL takes the
    elementwise max (pmax).  Associative + commutative, so ring merges
    compose in any grouping.
    """
    if not items:
        raise AnalysisError("merge_register_arrays needs at least one epoch")
    first = items[0]
    u64 = np.uint64
    lo = first["counts_lo"].astype(u64)
    total = lo + (first["counts_hi"].astype(u64) << u64(32))
    cms = first["cms"].copy()
    hll = first["hll"].copy()
    talk = first["talk_cms"].copy()
    for it in items[1:]:
        total = total + (
            it["counts_lo"].astype(u64) + (it["counts_hi"].astype(u64) << u64(32))
        )
        cms = (cms + it["cms"]).astype(np.uint32)
        np.maximum(hll, it["hll"], out=hll)
        talk = (talk + it["talk_cms"]).astype(np.uint32)
    return {
        "counts_lo": (total & u64(0xFFFFFFFF)).astype(np.uint32),
        "counts_hi": (total >> u64(32)).astype(np.uint32),
        "cms": cms,
        "hll": hll,
        "talk_cms": talk,
    }


def zero_arrays(n_keys: int, cfg: AnalysisConfig) -> dict[str, np.ndarray]:
    return dict(pipeline.state_to_host(pipeline.init_state_host(n_keys, cfg)))


# ---------------------------------------------------------------------------
# Key-space migration: old packed ruleset -> new packed ruleset.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MigrationMap:
    """How the old key/gid spaces map into a re-packed ruleset.

    Rule identity is ``(firewall, acl, rule text)`` — the index is
    exactly what renumbering changes, so it cannot be the identity.
    Duplicate identical texts within one ACL pair up in config order.
    Implicit-deny keys match by ACL identity.  ``key_map[old] == -1``
    means the old key has no home in the new space (rule deleted or
    rewritten): its counters go to the quarantine bucket.
    """

    key_map: np.ndarray  # [old n_keys] int64 -> new key id or -1
    gid_map: dict[int, int | None]  # old acl gid -> new gid (None = gone)
    old_n_keys: int
    new_n_keys: int
    #: which tenant's key space this map rewrites (DEFAULT_TENANT for the
    #: single-tenant service) — multi-tenant reloads migrate ONE lane's
    #: ring/cumulative images, and the stamp keeps a misdirected
    #: migration diagnosable in traces and tests
    tenant: str = DEFAULT_TENANT

    @property
    def identity(self) -> bool:
        return (
            self.old_n_keys == self.new_n_keys
            and bool((self.key_map == np.arange(self.old_n_keys)).all())
            and all(v == k for k, v in self.gid_map.items())
        )


def build_migration(
    old: pack_mod.PackedRuleset,
    new: pack_mod.PackedRuleset,
    tenant: str = DEFAULT_TENANT,
) -> MigrationMap:
    from collections import defaultdict, deque as _dq

    def ident(m):
        if m.implicit_deny:
            return (m.firewall, m.acl, None)
        return (m.firewall, m.acl, m.text)

    cand: dict[tuple, _dq] = defaultdict(_dq)
    for kid, m in enumerate(new.key_meta):
        cand[ident(m)].append(kid)
    key_map = np.full(old.n_keys, -1, dtype=np.int64)
    for kid, m in enumerate(old.key_meta):
        q = cand.get(ident(m))
        if q:
            key_map[kid] = q.popleft()
    gid_map = {
        gid: new.acl_gid.get(name) for name, gid in old.acl_gid.items()
    }
    return MigrationMap(key_map, gid_map, old.n_keys, new.n_keys, tenant)


def migrate_arrays(
    arrays: dict[str, np.ndarray],
    mig: MigrationMap,
    old: pack_mod.PackedRuleset,
    cfg: AnalysisConfig,
) -> tuple[dict[str, np.ndarray], dict[tuple, int]]:
    """Rewrite one register image into the new key space.

    Exact counts scatter through the (injective) key map — 64-bit, so
    quarantine accounting is exact to the line.  Per-key HLL rows travel
    with their key.  The two hashed sketches (per-key CMS, talker CMS)
    key by *hashed position*, which a renumbering invalidates wholesale:
    they reset to zero on a non-identity migration (they are estimate
    planes; the exact counters and the report's unused set never depend
    on them while ``exact_counts`` is on).  Returns the new image plus
    ``{(firewall, acl, index, text): hits}`` for every unmappable key
    with a nonzero count — the quarantine bucket.
    """
    if mig.identity:
        return {k: v.copy() for k, v in arrays.items()}, {}
    u64 = np.uint64
    old_tot = arrays["counts_lo"].astype(u64) + (
        arrays["counts_hi"].astype(u64) << u64(32)
    )
    s = cfg.sketch
    new_tot = np.zeros(mig.new_n_keys, dtype=u64)
    new_hll = np.zeros((mig.new_n_keys, s.hll_m), dtype=np.uint32)
    # the key map is injective (build_migration pops each new key at
    # most once), so a fancy-index assignment IS the scatter — the
    # reload pause stays O(n_keys) in numpy, not interpreter, time
    # (this runs once per ring epoch, partly under the publish lock)
    mapped = mig.key_map >= 0
    targets = mig.key_map[mapped]
    new_tot[targets] = old_tot[mapped]
    new_hll[targets] = arrays["hll"][mapped]
    quarantine: dict[tuple, int] = {}
    for kid in np.nonzero(~mapped & (old_tot > 0))[0]:
        m = old.key_meta[int(kid)]
        quarantine[(m.firewall, m.acl, m.index, m.text)] = int(old_tot[kid])
    return (
        {
            "counts_lo": (new_tot & u64(0xFFFFFFFF)).astype(np.uint32),
            "counts_hi": (new_tot >> u64(32)).astype(np.uint32),
            "cms": np.zeros((s.cms_depth, s.cms_width), dtype=np.uint32),
            "hll": new_hll,
            "talk_cms": np.zeros((s.talk_cms_depth, s.cms_width), dtype=np.uint32),
        },
        quarantine,
    )


def migrate_tracker_tables(
    tables: dict[int, dict[int, int]], mig: MigrationMap
) -> tuple[dict[int, dict[int, int]], int]:
    """Re-gid the talker summaries; returns (new tables, entries dropped)."""
    tag = int(pipeline.V6_ACL_TAG)
    out: dict[int, dict[int, int]] = {}
    dropped = 0
    for gid, table in tables.items():
        base = int(gid) & ~tag
        ng = mig.gid_map.get(base)
        if ng is None:
            dropped += len(table)
            continue
        dst = out.setdefault(ng | (int(gid) & tag), {})
        for src, est in table.items():
            dst[src] = max(dst.get(src, 0), est)
    return out, dropped


def _quarantine_totals(q: dict[tuple, int]) -> dict | None:
    """Report-facing image of a quarantine bucket (None when empty)."""
    if not q:
        return None
    return {
        "hits": int(sum(q.values())),
        "rules": [
            {"rule": f"{fw} {acl} {idx}", "text": text, "hits": int(h)}
            for (fw, acl, idx, text), h in sorted(q.items())
        ],
    }


def _merge_quarantine(dst: dict[tuple, int], src: dict[tuple, int]) -> None:
    for k, v in src.items():
        dst[k] = dst.get(k, 0) + v


# ---------------------------------------------------------------------------
# Window epochs + ring.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WindowEpoch:
    """One rotated window: register image + accounting + talker summary."""

    arrays: dict[str, np.ndarray]
    meta: dict  # id, lines, parsed, skipped, chunks, drops, incomplete...
    tracker_tables: dict[int, dict[int, int]]
    quarantine: dict[tuple, int] = dataclasses.field(default_factory=dict)


class WindowRing:
    """Ring of the last N window epochs (oldest evicted first)."""

    def __init__(self, size: int):
        if size < 1:
            raise AnalysisError(f"window ring size must be >= 1, got {size}")
        self.size = size
        self.epochs: deque[WindowEpoch] = deque(maxlen=size)

    def push(self, ep: WindowEpoch) -> None:
        self.epochs.append(ep)

    def last(self, k: int) -> list[WindowEpoch]:
        eps = list(self.epochs)
        return eps[-k:] if k > 0 else eps

    def window_ids(self) -> list[int]:
        return [ep.meta["id"] for ep in self.epochs]


class _Swag:
    """Two-stack sliding-window aggregate over one view's last ``size``
    register images (the SWAG trick): each pushed image is merged at
    most twice — once into the back accumulator, once into a suffix
    aggregate when the stacks flip — so querying the window's merge is
    O(1) amortized instead of re-folding ``size`` epochs.  Associativity
    of the merge laws makes the regrouped result bit-identical."""

    def __init__(self, size: int):
        self.size = size
        # front: (window id, suffix merge incl. self) with the OLDEST on
        # top; back: raw pushes since the last flip
        self.front: list[tuple[int, dict]] = []
        self.back: list[tuple[int, dict]] = []
        self.back_agg: dict | None = None

    def _len(self) -> int:
        return len(self.front) + len(self.back)

    def push(self, wid: int, arrays: dict) -> None:
        while self._len() >= self.size:
            self._pop_oldest()
        self.back.append((wid, arrays))
        self.back_agg = (
            arrays if self.back_agg is None
            else merge_register_arrays([self.back_agg, arrays])
        )

    def _pop_oldest(self) -> None:
        if not self.front:
            agg = None
            for wid, arrays in reversed(self.back):
                agg = (
                    arrays if agg is None
                    else merge_register_arrays([arrays, agg])
                )
                self.front.append((wid, agg))
            self.back = []
            self.back_agg = None
        self.front.pop()

    def query(self) -> tuple[list[int], dict | None]:
        """(window ids oldest-first, merged arrays or None when empty)."""
        ids = [w for w, _ in reversed(self.front)] + [w for w, _ in self.back]
        if self.front and self.back_agg is not None:
            agg = merge_register_arrays([self.front[-1][1], self.back_agg])
        elif self.front:
            agg = self.front[-1][1]
        else:
            agg = self.back_agg
        return ids, agg

    def clear(self) -> None:
        self.front = []
        self.back = []
        self.back_agg = None


class SuffixMergeCache:
    """Running suffix aggregates for the merged-K views ``_publish``
    re-renders every rotation.

    Correctness does not depend on the cache: :meth:`merged` returns
    arrays only when its retained window ids EXACTLY match the ring's,
    and ``None`` otherwise (cold start, post-reload migration, resume
    restore) — the caller falls back to the full fold and the cache
    self-heals as rotations refill it.  Only the ARRAYS are cached:
    tracker/meta/quarantine merging stays per-epoch in
    ``_render_merged`` so the rendered report is bit-identical to the
    uncached fold (bounded trackers evict order-dependently; dicts are
    cheap, registers are not).
    """

    def __init__(self, views: tuple[int, ...]):
        self._swags = {k: _Swag(k) for k in set(views)}
        self.hits = 0
        self.misses = 0

    def push(self, wid: int, arrays: dict) -> None:
        for s in self._swags.values():
            s.push(wid, arrays)

    def merged(self, k: int, window_ids: list[int]) -> dict | None:
        s = self._swags.get(k)
        if s is None:
            return None
        ids, agg = s.query()
        if agg is None or ids != window_ids:
            self.misses += 1
            return None
        self.hits += 1
        return agg

    def invalidate(self) -> None:
        """Reload migration / restore rewrote the epochs in place: the
        cached merges are old-key-space images, drop them all."""
        for s in self._swags.values():
            s.clear()


def render_range_report(
    agg, packed, cfg, *, topk: int, v6_digests=None, window_extra=None
):
    """The canonical range-report renderer: one stored/folded aggregate
    (runtime/epochstore.py ``EpochAgg``) -> a full Report.

    The talker tracker is rebuilt by offering the aggregate's UNBOUNDED
    max-deduped table in sorted order — deterministic and independent of
    how the aggregate was folded, which is what makes the segment-tree
    answer bit-identical to the naive linear fold (the property test and
    the bench both pin tree == naive through THIS function).
    """
    tracker = TopKTracker(cfg.sketch.topk_capacity)
    for acl in sorted(agg.tables):
        table = agg.tables[acl]
        for src in sorted(table):
            tracker.offer(int(acl), int(src), int(table[src]))
    s = agg.summary
    totals = {
        "lines_total": int(s["lines"]),
        "lines_matched": int(s["parsed"]),
        "lines_skipped": int(s["skipped"]),
        "chunks": int(s["chunks"]),
        "window": {
            "range": [int(agg.span[0]), int(agg.span[1]) - 1],
            "windows": int(s["windows"]),
            "drops": int(s["drops"]),
            "started_unix": s["started_unix"],
            "ended_unix": s["ended_unix"],
            **(
                {"incomplete": {
                    "windows": list(s["incomplete"]),
                    "drops": int(s["drops"]),
                }}
                if s["incomplete"]
                else {}
            ),
        },
    }
    if window_extra:
        totals["window"].update(window_extra)
    qt = _quarantine_totals(agg.quarantine)
    if qt:
        totals["quarantine"] = qt
    return pipeline.finalize(
        pipeline.AnalysisState(**agg.arrays), packed, cfg, tracker,
        topk=topk, totals=totals, v6_digests=v6_digests or {},
    )


# ---------------------------------------------------------------------------
# The serve driver.
# ---------------------------------------------------------------------------


class _ReloadFlushError(Exception):
    """Carrier: a device-step failure inside a reload's in-flight flush.

    NOT an atomic reload failure — the batcher tail was already consumed
    when the step raised, so treating it as a recoverable reload_error
    would publish a window missing delivered lines with no incomplete
    marker.  The reload path unwraps it and propagates the original
    typed error as a serve abort, exactly like the same step failure in
    the normal serve loop.
    """


class ServeDriver:
    """The long-running analysis service (one process, one mesh).

    Construction loads the packed ruleset and validates the config; the
    blocking :meth:`run` owns the device loop.  Tests drive it from a
    thread and talk to it over the loopback listeners / HTTP endpoint;
    the CLI ``serve`` subcommand runs it in the foreground with SIGHUP
    reload wired up.
    """

    def __init__(
        self,
        ruleset_prefix: str,
        cfg: AnalysisConfig,
        scfg: ServeConfig,
        *,
        topk: int = 10,
        mesh=None,
        ascfg: AutoscaleConfig | None = None,
    ):
        if ascfg is not None and cfg.mesh_shape != "flat":
            raise AnalysisError(
                "serve --autoscale resizes a flat single-host mesh; the "
                "hybrid DCN x ICI topology is the multi-host direction "
                "the elastic autoscaler grows along (drop --mesh hybrid)"
            )
        if ascfg is not None and mesh is not None:
            raise AnalysisError(
                "serve --autoscale owns the mesh; an explicit mesh "
                "argument cannot be resized"
            )
        if cfg.layout != "flat":
            raise AnalysisError(
                "serve supports layout='flat' only (the stacked group "
                "buffer's data-dependent emission cadence has no window "
                "boundary semantics yet)"
            )
        if cfg.coalesce != "off":
            raise AnalysisError(
                "serve does not support --coalesce yet; windowed batches "
                "are formed line-at-a-time at the listener edge"
            )
        if not scfg.listen:
            raise AnalysisError(
                "serve needs at least one --listen spec "
                "(udp:HOST:PORT, tcp:HOST:PORT, or tail:PATH)"
            )
        self.prefix = ruleset_prefix
        self.cfg = cfg
        self.scfg = scfg
        self.topk = topk
        self._mesh_arg = mesh
        self.ascfg = ascfg
        self._engine: PolicyEngine | None = None  # built in run()
        self.world = 0  # mesh extent, maintained across scale events
        # canonical-signal sampling state (runs with or without the
        # engine: the /metrics gauges are one source of truth either way)
        self.lines_consumed_total = 0
        self._gauge_lock = threading.Lock()
        self._as_next = 0.0
        self._as_last_t: float | None = None
        self._as_consumed_last = 0
        self._last_pressure = 0.0
        self._last_starved = 0.0
        self._pressure_sec = 0.0
        self._starved_sec = 0.0
        self._rate_inst = 0.0
        try:
            self.packed = pack_mod.load_packed(ruleset_prefix)
        except OSError as e:
            # typed so the CLI's bind-failure handler (except OSError
            # around construction) never misreports a bad --ruleset
            # prefix as "cannot bind --listen/--http"
            raise AnalysisError(
                f"cannot read packed ruleset {ruleset_prefix!r}: {e}"
            ) from e
        self.queue = LineQueue(scfg.queue_lines)
        self.listeners = ListenerSet(self.queue, list(scfg.listen))
        self.ring = WindowRing(scfg.ring)
        self._reload_req = threading.Event()
        self._stop_req = threading.Event()
        self._pub_lock = threading.Lock()
        self._published: dict[str, dict] = {}  # name -> report JSON obj
        self._window_reports: dict[int, dict] = {}
        # bind the HTTP endpoint here, like the listener sockets: a bad
        # --http port must be the documented clean bind error (exit 2,
        # before any listener thread starts), not a mid-run "serve I/O
        # failure" after traffic is already flowing
        self._http = None
        if scfg.http != "off":
            host, _, port = scfg.http.rpartition(":")
            try:
                self._http = _make_http_server((host, int(port)), self)
            except BaseException:
                # the listener sockets bound above have no owner yet —
                # a failed construction must release them
                self.listeners.close()
                raise
        self._http_thread = None
        self._watch_thread = None
        self._old_signals: dict = {}
        # service counters (cumulative across windows and reloads)
        self.windows_published = 0
        self.reloads = 0
        self.reload_errors = 0
        self.last_reload_error = ""
        self.total_lines = 0
        self.total_parsed = 0
        self.total_skipped = 0
        self.total_chunks = 0
        self.cum_quarantine: dict[tuple, int] = {}
        self.talker_entries_dropped = 0
        # static ruleset analysis plane (runtime/staticanalysis.py):
        # computed at start + on every reload when scfg.static_analysis
        self._sa = None
        self._static_obj: dict | None = None
        self._static_done_t: float | None = None
        self._static_duration = 0.0
        self.drops_restored = 0  # drops from checkpointed history (--resume)
        # degraded-mode plane (DESIGN §19): non-core subsystem failures
        # (static analysis, metrics snapshotter, devprof capture, report
        # publisher) mark the service degraded instead of aborting
        # ingest; recovery re-arms.  Own lock: _degrade/_recover are
        # called from paths that already hold _pub_lock.
        self._deg_lock = threading.Lock()
        self.degraded: dict[str, str] = {}  # subsystem -> last error
        self.degraded_events = 0
        self.recovered_events = 0
        # durable ingest WAL (DESIGN §19; opened in run() when scfg.wal)
        self.wal: WriteAheadLog | None = None
        self._wal_next = 0  # seq of the next line to consume
        self._wal_resume_seq = 0  # from the restored checkpoint
        self.wal_replayed = 0
        self.wal_lost_total = 0  # eviction/quarantine losses (exact)
        self.wal_lost_unknown = False
        # end-to-end latency SLO plane (DESIGN §20): listener receipt ->
        # window publish, log2 buckets merged across windows by addition
        # (lat_cum answers "is the service meeting its SLO" from
        # /metrics; the per-window histogram lands in totals.latency)
        self.lat_cum = LatencyHistogram()
        # cumulative incompleteness: EVERY reason a window was marked
        # (dead/stalled listeners included), not just queue drops — the
        # cumulative "unused ever" view must carry the marker whenever
        # any of its windows lost traffic
        self.cum_incomplete_reasons: list[str] = []
        self.cum_incomplete_windows: list[int] = []
        self._t0 = time.time()
        self._init_lineage_plane()

    def _init_lineage_plane(self) -> None:
        """Lineage + SLO + trend state shared by every serve driver.

        Split out of ``__init__`` because DistServeDriver is not a
        subclass — it borrows the publication methods unbound and calls
        this from its own constructor so ``_publish`` finds the same
        state on either class.
        """
        scfg = self.scfg
        # publication provenance (DESIGN §24): solo serve has no lease,
        # so term 0 / path "live" unless a subsystem overrides them
        self.term = 0
        self._path = "live"
        self._generation = 0  # reload/migration generation at rotate
        self._lineage_log = None  # LineageLog, opened in run()
        self._lineage_recent: dict[int, dict] = {}  # window id -> record
        self._lineage_merged: dict[int, dict] = {}  # merged-K k -> record
        self.lineage_records_total = 0
        # per-rule trend plane: rule key -> last emitted label
        self._trend_state: dict[str, str] = {}
        self.trend_events_total = 0
        # durable epoch store (DESIGN §25), opened in run() when
        # --epoch-store is armed; the range-query latency histogram and
        # the merged-K suffix cache ride here so DistServeDriver's
        # borrowed _publish/_attach_static find them too
        self.epoch_store: epochstore.EpochStore | None = None
        self.lat_range = LatencyHistogram()
        self._suffix = SuffixMergeCache(scfg.views) if scfg.views else None
        # SLO burn-rate engine (runtime/metrics.py), armed by --slo
        self.slo = (
            SloBurnEngine(SloPolicy.parse(scfg.slo)) if scfg.slo else None
        )

    # -- public control surface -----------------------------------------
    def request_reload(self) -> None:
        self._reload_req.set()

    def stop(self) -> None:
        self._stop_req.set()

    @property
    def http_address(self) -> tuple[str, int] | None:
        srv = self._http
        return tuple(srv.server_address[:2]) if srv is not None else None

    # -- degraded-mode plane (DESIGN §19) ---------------------------------
    def _degrade(self, subsystem: str, err: BaseException | str) -> None:
        """Mark a NON-CORE subsystem failed; ingest keeps serving."""
        msg = (
            err if isinstance(err, str)
            else f"{type(err).__name__}: {err}"
        )[:200]
        with self._deg_lock:
            first = subsystem not in self.degraded
            self.degraded[subsystem] = msg
            if first:
                self.degraded_events += 1
        if first:
            obs.instant("serve.degraded", args={
                "subsystem": subsystem, "error": msg,
            })
            obs.metric_event("serve.degraded", subsystem=subsystem, error=msg)

    def _recover(self, subsystem: str) -> None:
        """A later success of a degraded subsystem re-arms it."""
        with self._deg_lock:
            was = self.degraded.pop(subsystem, None)
            if was is not None:
                self.recovered_events += 1
        if was is not None:
            obs.instant("serve.recovered", args={"subsystem": subsystem})
            obs.metric_event("serve.recovered", subsystem=subsystem)

    def degraded_set(self) -> list[str]:
        with self._deg_lock:
            return sorted(self.degraded)

    def _check_metrics_health(self) -> None:
        """Poll the snapshotter's tick-error counters (cheap; loop tick)."""
        h = obs.metrics_health()
        if h is None:
            return
        if not h["alive"] or h["consec_errors"] > 0:
            self._degrade(
                "metrics",
                h["last_error"] or "metrics snapshotter thread died",
            )
        else:
            self._recover("metrics")

    # -- health / metrics ------------------------------------------------
    def health(self) -> dict:
        q = self.queue.snapshot()
        stalled = len(self.listeners.stalled(self.cfg.stall_timeout_sec))
        with self._pub_lock:
            # both mutate under this lock (reload + rotation on the serve
            # thread); an unlocked sum() here can die mid-iteration
            quarantine_hits = int(sum(self.cum_quarantine.values()))
            ring_windows = self.ring.window_ids()
        deg_subsystems = self.degraded_set()
        with self._deg_lock:
            deg_errors = dict(self.degraded)
        degraded = (
            q["dropped"] > 0
            or self.reload_errors > 0
            or stalled > 0
            or self.listeners.alive() < len(self.listeners.listeners)
            or bool(deg_subsystems)
        )
        return {
            "status": "degraded" if degraded else "ok",
            # the degraded SET is enumerable, not just a boolean: an
            # operator (or the soak harness) reads exactly which
            # non-core subsystems are down and which recovered
            "degraded_subsystems": deg_subsystems,
            **({"degraded_errors": deg_errors} if deg_errors else {}),
            "degraded_events": self.degraded_events,
            "recovered_events": self.recovered_events,
            "uptime_sec": round(time.time() - self._t0, 3),
            "windows_published": self.windows_published,
            "lines_total": self.total_lines,
            "queue": q,
            "listeners": {
                "n": len(self.listeners.listeners),
                "alive": self.listeners.alive(),
                "stalled": stalled,
                "addresses": self.listeners.addresses(),
            },
            "reloads": self.reloads,
            "reload_errors": self.reload_errors,
            **(
                {"last_reload_error": self.last_reload_error}
                if self.last_reload_error
                else {}
            ),
            "ruleset": {
                "n_rules": self.packed.n_rules,
                "n_acls": self.packed.n_acls,
                "n_keys": self.packed.n_keys,
            },
            "current_window": {
                "id": getattr(self, "win_id", 0),
                "pushed": getattr(self, "win_pushed", 0),
            },
            "window": {
                "mode": "lines" if self.scfg.window_lines else "sec",
                "length": self.scfg.window_lines or self.scfg.window_sec,
                "ring": self.scfg.ring,
                # under the publish lock: the serve thread pushes epochs
                # while HTTP handler threads read here
                "ring_windows": ring_windows,
            },
            "quarantine_hits": quarantine_hits,
            "world": self.world,
            **(
                {"autoscale": self._engine.summary()}
                if self._engine is not None
                else {}
            ),
        }

    def _sample_metrics(self) -> dict:
        return {
            **self.listeners.sample_metrics(),
            "windows_published": self.windows_published,
            "reloads": self.reloads,
            "lines_total": self.total_lines,
        }

    def metrics_gauges(self) -> dict:
        """Flat numeric gauges: ONE source of truth for the autoscale
        policy, the JSON ``/metrics`` endpoint, and the Prometheus
        text variant (``/metrics?format=prom``) external scrapers read —
        the policy and an operator's dashboard can never disagree about
        what the service saw."""
        q = self.queue.snapshot()
        eng = self._engine
        with self._gauge_lock:
            g = {
                "queue_depth": q["depth"],
                "queue_capacity": q["capacity"],
                "lines_received_total": q["received"],
                "drops_total": q["dropped"],
                "lines_consumed_total": self.lines_consumed_total,
                "lines_windowed_total": self.total_lines,
                "lines_per_sec": round(self._rate_inst, 1),
                "backpressure_frac": round(self._last_pressure, 4),
                "starved_frac": round(self._last_starved, 4),
                "backpressure_sec_total": round(self._pressure_sec, 3),
                "starved_sec_total": round(self._starved_sec, 3),
            }
        g.update({
            "windows_published": self.windows_published,
            "reloads_total": self.reloads,
            "reload_errors_total": self.reload_errors,
            "listeners_alive": self.listeners.alive(),
            "world": self.world,
            "degraded_subsystems": len(self.degraded_set()),
            "degraded_events_total": self.degraded_events,
            "recovered_events_total": self.recovered_events,
        })
        # end-to-end latency SLO gauges (DESIGN §20): p50/p90/p99 of the
        # cumulative receipt->publish histogram.  The prom variant ALSO
        # renders the full bucket histogram (render_latency_prom) — both
        # derive from the same counts, so a scraper's bucket-computed
        # p99 equals these gauges exactly
        g.update(self.lat_cum.gauges("latency_ingest_to_publish_"))
        # per-site retry attempt/recovery/giveup counters (DESIGN §19):
        # the same numbers the metrics JSONL sampler and the trace's
        # retry.attempt instants carry — one plane, three views
        g.update(retrypolicy.gauges())
        if self.wal is not None:
            w = self.wal.stats()
            g.update({
                "wal_appended_total": w["appended"],
                "wal_segments": w["segments"],
                "wal_bytes": w["bytes"],
                "wal_evicted_records_total": w["evicted_records"],
                "wal_replayed_total": self.wal_replayed,
                "wal_lost_total": self.wal_lost_total,
            })
        if self.epoch_store is not None:
            # store depth/compaction gauges + the range-query latency
            # quantiles: ONE dict for JSON and prom, parity pinned by
            # verify/registry.py::audit_epochstore
            g.update(self.epoch_store.gauges())
            g.update(self.lat_range.gauges("latency_range_query_"))
        if self._suffix is not None:
            g.update({
                "merged_suffix_hits_total": self._suffix.hits,
                "merged_suffix_misses_total": self._suffix.misses,
            })
        # device attribution + live device-memory headroom (DESIGN §14):
        # numeric gauges reach the prom variant too; unsupported memory
        # stats stay explicit nulls in the JSON (prom skips non-numerics)
        g.update(devprof.gauges())
        g.update(devprof.device_memory_gauges())
        # static-analysis freshness: how stale the published verdicts
        # are (age since the last completed run) and what a run costs —
        # an operator
        # alerting on age > reload cadence catches a wedged re-analysis
        if self.scfg.static_analysis and self._static_done_t is not None:
            g["static_analysis_age_sec"] = round(
                time.time() - self._static_done_t, 3
            )
            g["static_analysis_duration_sec"] = round(
                self._static_duration, 4
            )
        if eng is not None:
            g.update({
                "autoscale_decisions_total": len(eng.decisions),
                "autoscale_scale_out_total": sum(
                    1 for d in eng.decisions if d.direction == "out"
                ),
                "autoscale_scale_in_total": sum(
                    1 for d in eng.decisions if d.direction == "in"
                ),
                "autoscale_flaps_total": eng.flaps,
                "autoscale_budget_left": eng.budget_left,
            })
        # lineage + SLO planes (DESIGN §24): flat numerics, so the prom
        # gauge render carries them with JSON parity for free
        if self.scfg.lineage:
            g["lineage_records_total"] = self.lineage_records_total
            g["trend_events_total"] = self.trend_events_total
        if self.slo is not None:
            g.update(self.slo.gauges())
        return g

    def build_info_dict(self) -> dict:
        """``ra_build_info`` labels: what binary produced these numbers.

        Served verbatim on JSON ``/metrics`` (``build_info``) and as the
        standard value-1 labeled gauge on the prom variant; the two are
        parity-audited (verify/registry.py::audit_observability).
        """
        return build_info({
            "mesh": f"{self.cfg.mesh_shape}/{max(self.world, 1)}",
        })

    def render_latency_prom(self) -> str:
        """Prometheus HISTOGRAM exposition of the cumulative
        receipt->publish latency (``_bucket``/``_sum``/``_count`` with
        cumulative ``le`` labels), appended to the gauge rendering on
        ``/metrics?format=prom``."""
        out = self.lat_cum.render_prom("ra_serve_ingest_to_publish_seconds")
        if getattr(self, "epoch_store", None) is not None:
            out += self.lat_range.render_prom(
                "ra_serve_range_query_seconds"
            )
        return out

    def render_labeled_prom(self) -> str:
        """Labeled Prometheus families appended to ``/metrics?format=prom``.

        Every driver exports ``ra_build_info`` and (when ``--slo`` is
        armed) the per-objective burn-rate series; the distributed
        rank-0 driver (runtime/distserve.py) extends this with
        host-labeled series rendered from the SAME per-host JSON gauge
        blocks — the parity the registry audit
        (verify/registry.py::audit_distserve) pins.
        """
        out = render_build_info_prom(self.build_info_dict())
        if self.slo is not None:
            out += render_prom_labeled(
                self.slo.labeled_gauges(),
                prefix="ra_serve_",
                label="objective",
            )
        return out

    # -- report access (HTTP + tests) ------------------------------------
    def published(self, name: str) -> dict | None:
        with self._pub_lock:
            return self._published.get(name)

    def window_report(self, wid: int) -> dict | None:
        with self._pub_lock:
            return self._window_reports.get(wid)

    def merged_report_obj(self, k: int) -> dict | None:
        """Merge the last ``k`` ring epochs into one report (on demand).

        Snapshots the epochs AND the ruleset under the publish lock,
        then renders outside it: the (possibly slow) merge + finalize
        must not block the serve loop's rotation publish, and a reload
        swapping the key space mid-render must not mix old arrays with
        the new ruleset.  Shallow refs suffice — a reload REBINDS epoch
        arrays/tables, never mutates them in place — except quarantine,
        which is merged in place and therefore copied.
        """
        with self._pub_lock:
            eps = [
                WindowEpoch(
                    arrays=ep.arrays,
                    meta=dict(ep.meta),
                    tracker_tables=ep.tracker_tables,
                    quarantine=dict(ep.quarantine),
                )
                for ep in self.ring.last(k)
            ]
            packed = self.packed
            # same snapshot as the ruleset: a reload completing mid-
            # render must not join new-key-space verdicts onto this
            # old-key-space report by key_id
            sa_obj = self._static_obj
        if not eps:
            return None
        obj = json.loads(self._render_merged(eps, packed).to_json())
        if sa_obj is not None:
            from . import staticanalysis

            staticanalysis.attach_static_obj(obj, sa_obj, strict=False)
        return obj

    # -- static analysis plane (ISSUE 12) ---------------------------------
    def _compute_static(self, packed, reuse):
        """Run the analyzer (compute only — nothing published on failure)."""
        from . import staticanalysis

        t0 = time.monotonic()
        with obs.span("serve.static_analysis"):
            sa = staticanalysis.analyze_ruleset(
                packed,
                witness_budget=self.scfg.static_witness_budget,
                reuse=reuse,
            )
        return sa, time.monotonic() - t0

    def _install_static(self, sa, obj: dict, duration: float) -> None:
        """Swap in a COMPLETE verdict set.  Caller holds ``_pub_lock`` —
        the reload path installs this INSIDE its one locked ruleset swap
        so an HTTP render can never join old-ruleset verdicts onto
        new-ruleset key ids (or vice versa)."""
        self._sa = sa
        self._static_obj = obj
        self._published["static"] = obj
        self._static_done_t = time.time()
        self._static_duration = duration
        # a complete verdict set re-arms a degraded static plane (the
        # initial analysis failed; a reload's re-analysis succeeded)
        self._recover("static_analysis")

    def _static_side_effects(self, obj: dict, duration: float) -> None:
        """Off-lock tail of a static publish: disk + metrics."""
        self._write_json("static.json", obj)
        obs.metric_event(
            "serve.static",
            dead=obj["meta"]["dead"],
            reused_acls=obj["meta"]["reused_acls"],
            duration_sec=round(duration, 4),
        )

    def _publish_static(self, packed, sa, duration: float) -> None:
        obj = sa.to_obj(packed)
        with self._pub_lock:
            self._install_static(sa, obj, duration)
        self._static_side_effects(obj, duration)

    def _attach_static(self, obj: dict, *, strict: bool) -> dict:
        """Join the live verdicts into a report object (no-op when the
        analyzer is off).  ``strict`` reports raise the typed
        AnalyzerContradiction on hit+dead-verdict; non-strict (counters
        spanning a reload, restored history, cumulative/merged views)
        record contradictions in ``totals.static`` instead."""
        sa_obj = self._static_obj
        if sa_obj is None:
            return obj
        from . import staticanalysis

        obj = staticanalysis.attach_static_obj(obj, sa_obj, strict=strict)
        store = getattr(self, "epoch_store", None)
        if store is not None:
            # the quiet-horizon join (DESIGN §25): safe_to_delete
            # verdicts cite WHEN each rule last hit inside retained
            # history, or that it never has
            epochstore.attach_last_hit(obj, store)
        return obj

    # -- internals -------------------------------------------------------
    def _render_merged(self, eps: list[WindowEpoch], packed, arrays=None):
        # ``arrays`` lets _publish hand in the SuffixMergeCache's
        # precomputed merge (bit-identical by associativity); tracker/
        # meta/quarantine below stay per-epoch so the rendered report
        # is byte-equal either way
        if arrays is None:
            arrays = merge_register_arrays([ep.arrays for ep in eps])
        tracker = TopKTracker(self.cfg.sketch.topk_capacity)
        for ep in eps:
            for acl, table in ep.tracker_tables.items():
                for src, est in table.items():
                    tracker.offer(int(acl), int(src), int(est))
        drops = sum(ep.meta.get("drops", 0) for ep in eps)
        incomplete = [
            ep.meta["id"] for ep in eps if ep.meta.get("incomplete")
        ]
        q: dict[tuple, int] = {}
        for ep in eps:
            _merge_quarantine(q, ep.quarantine)
        totals = {
            "lines_total": int(sum(ep.meta["lines"] for ep in eps)),
            "lines_matched": int(sum(ep.meta["parsed"] for ep in eps)),
            "lines_skipped": int(sum(ep.meta["skipped"] for ep in eps)),
            "chunks": int(sum(ep.meta["chunks"] for ep in eps)),
            "window": {
                "merged_windows": [ep.meta["id"] for ep in eps],
                "mode": "lines" if self.scfg.window_lines else "sec",
                "length": self.scfg.window_lines or self.scfg.window_sec,
                "drops": int(drops),
                **(
                    {"incomplete": {"windows": incomplete, "drops": int(drops)}}
                    if incomplete
                    else {}
                ),
            },
        }
        qt = _quarantine_totals(q)
        if qt:
            totals["quarantine"] = qt
        deg = self.degraded_set()
        if deg:
            totals["degraded"] = deg
        return pipeline.finalize(
            pipeline.AnalysisState(**arrays), packed, self.cfg, tracker,
            topk=self.topk, totals=totals, v6_digests=self._v6_digests,
        )

    def _write_json(self, name: str, obj: dict) -> None:
        """Publish one JSON artifact under the serve.publish retry policy.

        The publisher is a NON-CORE subsystem: a transient disk fault
        retries with backoff, and an exhausted budget (or a permanent
        error) degrades the publisher — the in-memory endpoints keep
        serving every report — instead of aborting ingest.  The next
        successful write re-arms it.
        """
        path = os.path.join(self.scfg.serve_dir, name)
        tmp = path + ".tmp"

        def _write():
            faults.fire("serve.publish.fail")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(obj, f, indent=2)
            os.replace(tmp, path)

        try:
            retrypolicy.call("serve.publish", _write)
        except (OSError, AnalysisError) as e:
            self._degrade("publisher", e)
            return
        self._recover("publisher")

    # -- the run loop ----------------------------------------------------
    def run(self) -> dict:
        """Serve until stopped; returns a summary dict (also written to
        ``serve_dir/summary.json``)."""
        import jax  # deferred: keep construction backend-free

        from ..parallel import mesh as mesh_lib
        from ..parallel.step import make_parallel_step, make_parallel_step6
        from .metrics import DispatchTimer

        scfg = self.scfg
        os.makedirs(scfg.serve_dir, exist_ok=True)
        armed_here = faults.arm_spec(self.cfg.fault_plan)
        retrypolicy.configure(self.cfg.retry_policy)
        if self.cfg.blackbox_dir:
            # always-on flight recorder (DESIGN §20): the ring runs for
            # the service's lifetime; a typed abort / stall / crash
            # dumps it beside the serve dir for the doctor
            flightrec.arm(self.cfg.blackbox_dir, role="serve")
        aborted: BaseException | None = None
        try:
            # EVERYTHING after arming is inside the try: a setup failure
            # (mesh, batch geometry, CheckpointMismatch from --resume)
            # must still disarm the fault plan and close the pre-bound
            # listener/HTTP sockets, exactly like a mid-run abort
            self._mesh_lib = mesh_lib
            self._devices = list(jax.devices())
            if self.ascfg is not None:
                a = self.ascfg
                max_w = a.max_world or len(self._devices)
                if max_w > len(self._devices):
                    raise AnalysisError(
                        f"--autoscale-max {max_w} exceeds the "
                        f"{len(self._devices)} available devices"
                    )
                # worlds are restricted to DIVISORS of the maximum: the
                # batch geometry is padded to max_w once and never
                # changes, so every chunk boundary — and therefore the
                # full report, candidate tables included — is
                # bit-identical across scale events (DESIGN §13)
                self._ladder = world_ladder(
                    a.min_world, max_w, divisors_of=max_w
                )
                start = a.initial_world or self._ladder[0]
                if start not in self._ladder:
                    raise AnalysisError(
                        f"--autoscale-initial {start} is not on the world "
                        f"ladder {self._ladder} (divisors of {max_w})"
                    )
                self._fp_world = max_w
                self.world = start
                mesh = mesh_lib.make_mesh(
                    self._devices[:start], axis=self.cfg.mesh_axis
                )
                self.batch_size = (
                    (self.cfg.batch_size + max_w - 1) // max_w
                ) * max_w
                self._engine = PolicyEngine(a, world=start, ladder=self._ladder)
            else:
                mesh = self._mesh_arg or mesh_lib.make_mesh(
                    axis=self.cfg.mesh_axis,
                    topology=self.cfg.mesh_shape,
                    dcn=self.cfg.mesh_dcn,
                )
                self.world = mesh_lib.data_extent(mesh)
                self._fp_world = self.world
                self.batch_size = mesh_lib.pad_batch_size(
                    self.cfg.batch_size, mesh, self.cfg.mesh_axis
                )
            self.mesh = mesh
            if self.packed.bindings_out and self.batch_size < 2:
                raise AnalysisError(
                    "batch_size must be >= 2 when out-direction "
                    "access-groups are bound"
                )
            # closures read self.mesh so a scale event only has to
            # rebind it before re-installing the ruleset
            self._make_step = lambda p: make_parallel_step(
                self.mesh, self.cfg, p.n_keys
            )
            self._make_step6 = lambda p: make_parallel_step6(
                self.mesh, self.cfg, p.n_keys
            )
            self._dispatch = DispatchTimer()
            self._install_ruleset(self.packed)
            self._v6_digests: dict[int, int] = {}
            self._v6rows: list = []
            self._fp = self._fingerprint(self.packed)
            if scfg.static_analysis:
                # initial analysis: a failure here (incl. the
                # analyze.tile fault site) DEGRADES the static plane —
                # the service keeps ingesting with /health naming the
                # loss, and the endpoint still NEVER serves a partial
                # verdict table (there simply is none until a reload's
                # re-analysis succeeds and re-arms the subsystem)
                try:
                    sa, dur = self._compute_static(self.packed, reuse=None)
                except AnalysisError as e:
                    self._degrade("static_analysis", e)
                else:
                    self._publish_static(self.packed, sa, dur)

            # fresh window scaffolding (possibly replaced by resume below)
            self.win_id = 0
            self.cum_arrays = zero_arrays(self.packed.n_keys, self.cfg)
            self.cum_tracker = TopKTracker(self.cfg.sketch.topk_capacity)
            if self.cfg.resume:
                self._restore_ring()
            if scfg.wal:
                self.wal = WriteAheadLog(
                    scfg.wal_dir or os.path.join(scfg.serve_dir, "wal"),
                    segment_bytes=scfg.wal_segment_bytes,
                    budget_bytes=scfg.wal_budget_bytes,
                )
                if not self.cfg.resume:
                    # a fresh (non-resume) run starts a fresh spool: a
                    # previous analysis's stale tail must neither replay
                    # nor grow the directory forever
                    self.wal.reset()
                self._wal_next = (
                    self._wal_resume_seq if self.cfg.resume
                    else self.wal.next_seq
                )
            if scfg.epoch_store:
                # durable history (DESIGN §25): fresh runs reset like
                # the WAL; resumed runs re-bind and the frontier check
                # makes a window-id gap a typed startup refusal
                self.epoch_store = epochstore.EpochStore(
                    scfg.epoch_store,
                    budget_bytes=scfg.epoch_store_budget_bytes,
                    trend_threshold=scfg.trend_threshold,
                )
                if not self.cfg.resume:
                    self.epoch_store.reset()
                self.epoch_store.bind_base(self.win_id)
                self.epoch_store.set_labels(
                    self._rule_labels(self.packed)
                )

            if scfg.lineage:
                # provenance ledger (DESIGN §24): O_APPEND jsonl beside
                # the window files; opening it is CORE setup — a serve
                # dir we cannot append lineage to cannot publish
                lpath = os.path.join(scfg.serve_dir, LineageLog.NAME)
                if self.cfg.resume:
                    # repopulate the ring-retained /lineage view from
                    # the ledger (window reports re-render from epochs;
                    # provenance re-reads from its own log)
                    live = set(self.ring.window_ids())
                    for r in LineageLog.read(lpath):
                        if r.get("kind") != "merged" and r.get("window") in live:
                            self._lineage_recent[r["window"]] = r
                            self.lineage_records_total += 1
                else:
                    # fresh (non-resume) run, fresh ledger — the WAL
                    # reset discipline, applied to provenance
                    try:
                        os.remove(lpath)
                    except OSError:
                        pass
                self._lineage_log = LineageLog(lpath)
            obs.register_sampler("listener", self._sample_metrics)
            obs.register_sampler("serve", self.metrics_gauges)
            self.listeners.start()
            self._begin_window()
            if self.wal is not None and self.cfg.resume:
                self._replay_wal()
            self._start_http()
            self._start_watcher()
            self._install_signals()
            self._write_json("endpoint.json", {
                "pid": os.getpid(),
                "http": list(self.http_address) if self.http_address else None,
                "listeners": self.listeners.addresses(),
                "serve_dir": os.path.abspath(scfg.serve_dir),
            })
            self._loop()
        except BaseException as e:
            aborted = e
            raise
        finally:
            try:
                self._teardown(aborted)
            finally:
                # disarm on abort paths too: a plan this run armed must
                # not leak into later runs in the same process
                if armed_here:
                    faults.disarm()
        summary = {
            "windows_published": self.windows_published,
            "lines_total": self.total_lines,
            "drops": self.queue.snapshot()["dropped"],
            "reloads": self.reloads,
            "reload_errors": self.reload_errors,
            "quarantine_hits": int(sum(self.cum_quarantine.values())),
            "serve_dir": os.path.abspath(scfg.serve_dir),
            "world": self.world,
            "degraded": self.degraded_set(),
            "degraded_events": self.degraded_events,
            "recovered_events": self.recovered_events,
            "retry": retrypolicy.counters(),
            **(
                {"autoscale": self._engine.summary()}
                if self._engine is not None
                else {}
            ),
        }
        if self.wal is not None:
            summary["wal"] = {
                **self.wal.stats(),
                "replayed": self.wal_replayed,
                "lost": self.wal_lost_total,
                "lost_unknown": self.wal_lost_unknown,
            }
        if self.epoch_store is not None:
            summary["epoch_store"] = self.epoch_store.stats()
        self._write_json("summary.json", summary)
        return summary

    def _fingerprint(self, packed) -> str:
        # under autoscale the fingerprint pins the LADDER MAXIMUM, not
        # the live world: registers are replicated/world-independent, so
        # a ring checkpoint taken at world 2 must resume at world 8 (and
        # vice versa) without a mismatch refusal
        return (
            ckpt.fingerprint(packed, self.cfg, self._fp_world, 0) + "-serve"
        )

    def _install_ruleset(self, packed) -> None:
        """Ship (or re-ship) the rule tensor + step programs."""
        self.packed = packed
        self.dev_rules = pipeline.ship_ruleset(
            packed, match_impl=self.cfg.match_impl
        )
        self.step = self._make_step(packed)
        self.step6 = None
        self.dev_rules6 = None
        if packed.has_v6:
            self.dev_rules6 = pipeline.ship_ruleset6(packed)
            self.step6 = self._make_step6(packed)

    # -- window lifecycle ------------------------------------------------
    def _begin_window(self) -> None:
        from .stream import LineBatcher

        self.state = pipeline.init_state(self.packed.n_keys, self.cfg)
        self.tracker = TopKTracker(self.cfg.sketch.topk_capacity)
        self.pending: deque[pipeline.ChunkOut] = deque()
        packer = pack_mod.LinePacker(self.packed)
        self.batcher = LineBatcher(
            packer, self.packed.has_v6, self._v6rows, self._v6_digests,
            self.batch_size,
        )
        self.n_chunks = 0  # window-local: the candidate-table salt, reset
        # so a window replays exactly like an offline run over its lines
        self.win_lines = 0  # lines committed to emitted batches
        self.win_pushed = 0  # lines handed to the batcher
        self.win_reloads = 0
        self.win_quarantine: dict[tuple, int] = {}
        self._win_wal_drops = 0  # WAL eviction/quarantine losses replayed here
        self._win_wal_unknown = False
        self._buf6 = None
        self._fill6 = 0
        self._win_t0 = time.time()
        # interval math runs on the monotonic clock (an NTP step must
        # never produce a negative window rate); the wall stamps above
        # stay for operator correlation only
        self._win_t0_mono = time.monotonic()
        # receipt timestamps of this window's consumed lines, decimated
        # by powers of two past the cap so memory stays bounded on huge
        # wall-clock windows (each retained stamp then counts for
        # ``stride`` lines in the histogram — counts stay representative)
        self._win_lat = LatencyHistogram()
        self._win_receipts: list[float] = []
        self._recv_stride = 1
        self._recv_i = 0
        flightrec.cursor(window=self.win_id)
        # the drop baseline carries over from the previous window's close
        # (when there is one) so a drop landing DURING rotation/publish
        # still charges to exactly one window, never the gap between two
        base = getattr(self, "_next_drops_base", None)
        self._drops_at_start = (
            base if base is not None else self.queue.snapshot()["dropped"]
        )
        self._listeners_ok_at_start = (
            self.listeners.alive() == len(self.listeners.listeners)
        )
        self._win_saw_stall = False
        # lineage (DESIGN §24): the first WAL seq this window can cover;
        # rotation stamps the exclusive hi bound from the same cursor,
        # so [lo, hi) is exactly the delivered range.  The previous
        # window's lo survives one rotation for the _emit_epoch hook,
        # which runs AFTER the next window opens (distserve ships it)
        self._prev_win_wal_lo = int(getattr(self, "_win_wal_lo", 0))
        self._win_wal_lo = int(self._wal_next)

    #: receipt stamps retained per window before stride decimation
    _RECEIPT_CAP = 1 << 16

    def _note_receipt(self, t_recv: float) -> None:
        """Retain one consumed line's receipt stamp for the window's
        ingest->publish latency histogram (stride-decimated, bounded)."""
        if self._recv_i % self._recv_stride == 0:
            self._win_receipts.append(t_recv)
            if len(self._win_receipts) >= self._RECEIPT_CAP:
                # halve retention, double the stride: deterministic,
                # bounded, and each stamp's histogram weight doubles
                self._win_receipts = self._win_receipts[::2]
                self._recv_stride *= 2
        self._recv_i += 1

    def _drain(self, out: pipeline.ChunkOut) -> None:
        self.tracker.offer_chunk(
            np.asarray(out.cand_acl),
            np.asarray(out.cand_src),
            np.asarray(out.cand_est),
        )

    def _kind(self, base: str) -> str:
        # per-world dispatch kinds: each scale rung compiles its own
        # program, and the compile-vs-sustained split must price each
        # geometry's first dispatches, not conflate them
        return base if self._engine is None else f"{base}w{self.world}"

    def _run_chunk(self, batch_np: np.ndarray) -> None:
        wire = pack_mod.compact_batch(batch_np)
        dev = self._mesh_lib.shard_batch(self.mesh, wire, self.cfg.mesh_axis)
        self.state, out = self._dispatch.first(
            self._kind("v4"), self.step, self.state, self.dev_rules, dev,
            self.n_chunks,
        )
        self.pending.append(out)
        if len(self.pending) > 2:
            self._drain(self.pending.popleft())
        self.n_chunks += 1

    def _run_chunk6(self, batch6_np: np.ndarray) -> None:
        dev = self._mesh_lib.shard_batch(self.mesh, batch6_np, self.cfg.mesh_axis)
        self.state, out = self._dispatch.first(
            self._kind("v6"), self.step6, self.state, self.dev_rules6, dev,
            self.n_chunks,
        )
        self.pending.append(out)
        if len(self.pending) > 2:
            self._drain(self.pending.popleft())
        self.n_chunks += 1

    def _stage_v6(self) -> None:
        # mirror of _run_core_impl.stage_v6: drain staged rows, step full
        # v6 chunks; partial chunks wait for flush
        if self.step6 is None:
            return
        if not self._v6rows:
            return
        # drain in place: the batcher holds a reference to this list
        rows = self._v6rows[:]
        del self._v6rows[:]
        i = 0
        while i < len(rows):
            if self._buf6 is None:
                self._buf6 = np.zeros(
                    (pack_mod.TUPLE6_COLS, self.batch_size), dtype=np.uint32
                )
            take = min(self.batch_size - self._fill6, len(rows) - i)
            self._buf6[:, self._fill6:self._fill6 + take] = np.asarray(
                rows[i:i + take], dtype=np.uint32
            ).T
            self._fill6 += take
            i += take
            if self._fill6 == self.batch_size:
                self._run_chunk6(self._buf6)
                self._buf6 = None
                self._fill6 = 0

    def _flush_v6(self) -> None:
        if self.step6 is None:
            return
        self._stage_v6()
        if self._fill6:
            self._run_chunk6(self._buf6)
            self._buf6 = None
            self._fill6 = 0

    def _consume_event(self, ev: tuple[np.ndarray | None, int]) -> None:
        batch_np, n_raw = ev
        if batch_np is None:
            self.win_lines += n_raw
            obs.add_lines(n_raw)
            self._stage_v6()
            return
        self._run_chunk(batch_np)
        self._stage_v6()
        self.win_lines += n_raw
        obs.add_lines(n_raw)

    def _flush_inflight(self) -> None:
        """Step everything consumed so far (rotation/reload barrier)."""
        tail = self.batcher.flush()
        if tail is not None:
            self._consume_event(tail)
        self._flush_v6()
        pipeline.sync_state(self.state)
        while self.pending:
            self._drain(self.pending.popleft())

    # -- durable ingest WAL (DESIGN §19) ----------------------------------
    def _replay_wal(self) -> None:
        """Replay the spool tail past the restored checkpoint's seq.

        Runs BEFORE live consumption: the interrupted window (and, at a
        sparser checkpoint cadence, any rotated-but-uncheckpointed
        windows — ids and boundaries are deterministic) rebuilds from
        the on-disk records through the NORMAL consume path, so its
        eventual report is bit-identical to what an uninterrupted run
        would have published over the same delivered lines.  Eviction
        gaps and quarantined segments surface as exactly-counted drops
        with the ``wal_lost`` incomplete reason — never a silent gap.
        """
        assert self.wal is not None
        n = 0
        noted = 0  # losses already charged to a window
        # lineage: windows that rotate DURING replay publish with
        # path="replay" — same core record, honest envelope
        self._path = "replay"
        with obs.span("serve.wal.replay", from_seq=self._wal_resume_seq):
            # tenant keys in the records are the tenancy plane's concern
            # (runtime/tenantserve.py); the single-tenant driver replays
            # every delivered line regardless of key
            for seq, line, _tenant in self.wal.replay(self._wal_resume_seq):
                # charge losses to the window open when they were
                # OBSERVED (head-eviction gap -> the first replayed
                # window; a mid-chain quarantine -> the window at that
                # point), not blanket-attributed at the end
                if self.wal.replay_lost > noted:
                    self._note_wal_loss(self.wal.replay_lost - noted, False)
                    noted = self.wal.replay_lost
                for ev in self.batcher.push(line):
                    self._consume_event(ev)
                # replayed lines' true receipt stamps died with the
                # previous process; the replay instant is the honest
                # (conservative) receipt stand-in
                self._note_receipt(time.monotonic())
                self.win_pushed += 1
                self.lines_consumed_total += 1
                self._wal_next = seq + 1
                n += 1
                if (
                    self.scfg.window_lines
                    and self.win_pushed >= self.scfg.window_lines
                ):
                    self._rotate()
        self._path = "live"
        self.wal_replayed = n
        if self.wal.replay_lost > noted or self.wal.replay_lost_unknown:
            self._note_wal_loss(
                self.wal.replay_lost - noted, self.wal.replay_lost_unknown
            )
        obs.metric_event(
            "serve.wal.replay", replayed=n, lost=self.wal.replay_lost,
            lost_unknown=self.wal.replay_lost_unknown,
            quarantined=len(self.wal.quarantined),
        )

    def _note_wal_loss(self, lost: int, unknown: bool) -> None:
        self._win_wal_drops += lost
        self.wal_lost_total += lost
        if unknown:
            self._win_wal_unknown = True
            self.wal_lost_unknown = True

    # -- rotation + publication ------------------------------------------
    def _window_meta(self, *, partial: bool) -> dict:
        drops = self.queue.snapshot()["dropped"] - self._drops_at_start
        self._next_drops_base = self._drops_at_start + drops
        listeners_ok = (
            self.listeners.alive() == len(self.listeners.listeners)
        )
        reasons = []
        if drops > 0:
            reasons.append("dropped_lines")
        if self._listeners_ok_at_start and not listeners_ok:
            reasons.append("listener_died")
        if not self._listeners_ok_at_start:
            reasons.append("listener_down")
        if self._win_saw_stall or self.listeners.stalled(
            self.cfg.stall_timeout_sec
        ):
            reasons.append("listener_stalled")
        if self._win_wal_drops or self._win_wal_unknown:
            # WAL eviction/quarantine losses replayed into this window:
            # exactly counted where seq arithmetic pins them; "unknown"
            # marks a corrupt final segment whose tail nothing pins
            reasons.append("wal_lost")
            drops += self._win_wal_drops
        packer = self.batcher.packer
        meta = {
            "id": self.win_id,
            "mode": "lines" if self.scfg.window_lines else "sec",
            "length": self.scfg.window_lines or self.scfg.window_sec,
            "lines": self.win_lines,
            "parsed": packer.parsed,
            "skipped": packer.skipped,
            "chunks": self.n_chunks,
            "drops": int(drops),
            "reloads": self.win_reloads,
            "started_unix": round(self._win_t0, 3),
            "ended_unix": round(time.time(), 3),
            # monotonic-derived: the window's lines/s can never go
            # negative or inflate across an NTP step (the wall stamps
            # above are correlation aids, not interval sources)
            "elapsed_sec": round(time.monotonic() - self._win_t0_mono, 4),
        }
        if self._win_wal_drops or self._win_wal_unknown:
            meta["wal_lost"] = int(self._win_wal_drops)
            if self._win_wal_unknown:
                meta["wal_lost_unknown"] = True
        if partial:
            meta["partial"] = True
        if reasons:
            # the typed WindowIncomplete marker: this window's traffic is
            # known-incomplete, so "0 hits" here must not read as unused
            meta["incomplete"] = {"drops": int(drops), "reasons": reasons}
        return meta

    def _window_totals(
        self,
        meta: dict,
        quarantine: dict[tuple, int],
        latency: dict | None = None,
    ) -> dict:
        # monotonic-derived where available (live rotations); restored
        # epochs predate the stamp and fall back to the wall difference
        elapsed = meta.get(
            "elapsed_sec", max(meta["ended_unix"] - meta["started_unix"], 0.0)
        )
        totals = {
            "lines_total": meta["lines"],
            "lines_matched": meta["parsed"],
            "lines_skipped": meta["skipped"],
            "chunks": meta["chunks"],
            "elapsed_sec": round(elapsed, 4),
            "lines_per_sec": (
                round(meta["lines"] / elapsed, 1) if elapsed > 0 else 0.0
            ),
            "window": meta,
        }
        if latency:
            # receipt->publish percentiles for THIS window (DESIGN §20;
            # VOLATILE for identity like every timing total)
            totals["latency"] = {"ingest_to_publish": latency}
        qt = _quarantine_totals(quarantine)
        if qt:
            totals["quarantine"] = qt
        deg = self.degraded_set()
        if deg:
            # the report itself says which non-core subsystems were down
            # while these counters were earned (volatile for identity)
            totals["degraded"] = deg
        return totals

    def _render_window_obj(self, ep: WindowEpoch) -> dict:
        """Re-render one epoch's window report (resume repopulation)."""
        tracker = TopKTracker(self.cfg.sketch.topk_capacity)
        for acl, table in ep.tracker_tables.items():
            for src, est in table.items():
                tracker.offer(int(acl), int(src), int(est))
        rep = pipeline.finalize(
            pipeline.AnalysisState(**ep.arrays), self.packed, self.cfg,
            tracker, topk=self.topk,
            totals=self._window_totals(ep.meta, ep.quarantine),
            v6_digests=self._v6_digests,
        )
        # restored history may predate the analyzed ruleset: annotate,
        # never abort, on a contradiction
        return self._attach_static(json.loads(rep.to_json()), strict=False)

    def _rotate(self, *, partial: bool = False) -> None:
        # a CLOSED devprof capture window parses here, between windows —
        # never on the ingest path, and never closing an open window
        # early (runtime/devprof.py; the gauges go live next scrape)
        cap = devprof.active_capture()
        if cap is not None:
            try:
                cap.poll()
            except AnalysisError as e:
                # devprof is non-core: a failed capture parse degrades
                # the attribution plane, never the ingest it observes
                self._degrade("devprof", e)
        with obs.span("serve.rotate", window=self.win_id):
            self._flush_inflight()
            # the publish instant of this window's latency clock: every
            # retained receipt stamp becomes one stride-weighted sample
            # (receipt -> the rotation that makes the line's effect
            # visible in a published report)
            t_pub = time.monotonic()
            for t_recv in self._win_receipts:
                self._win_lat.record(
                    max(t_pub - t_recv, 0.0), n=self._recv_stride
                )
            self.lat_cum.merge(self._win_lat)
            win_latency = (
                self._win_lat.summary() if self._win_lat.count else None
            )
            win_hist = self._win_lat  # survives _begin_window's reset
            meta = self._window_meta(partial=partial)
            arrays = pipeline.state_to_host(self.state)
            ep = WindowEpoch(
                arrays=arrays,
                meta=meta,
                tracker_tables=self.tracker.tables(),
                quarantine=dict(self.win_quarantine),
            )
            rep = pipeline.finalize(
                pipeline.AnalysisState(**arrays), self.packed, self.cfg,
                self.tracker, topk=self.topk,
                totals=self._window_totals(
                    meta, self.win_quarantine, latency=win_latency
                ),
                v6_digests=self._v6_digests,
            )
            # strict contradiction check only when every counter in this
            # window was earned under the analyzed ruleset (no reload
            # mid-window) AND the counters are exact — CMS-estimated
            # hits can collide above zero on a genuinely dead rule;
            # hit+dead-verdict then aborts typed
            rep_obj = self._attach_static(
                json.loads(rep.to_json()),
                strict=meta.get("reloads", 0) == 0 and self.cfg.exact_counts,
            )
            if self.scfg.lineage:
                # provenance (DESIGN §24): assembled while the closed
                # window's WAL cursor + quarantine are still live state
                rep_obj["totals"]["lineage"] = self._assemble_lineage(
                    meta, self.win_quarantine
                )
            if meta.get("incomplete"):
                self.cum_incomplete_windows.append(meta["id"])
                for r in meta["incomplete"]["reasons"]:
                    if r not in self.cum_incomplete_reasons:
                        self.cum_incomplete_reasons.append(r)
            with self._pub_lock:
                self.ring.push(ep)
                prev = self._published.get("report")
                # quarantine merges under the lock: /health sums this
                # dict from HTTP handler threads
                _merge_quarantine(self.cum_quarantine, self.win_quarantine)
            # cumulative accounting
            self.cum_arrays = merge_register_arrays([self.cum_arrays, arrays])
            for acl, table in ep.tracker_tables.items():
                for src, est in table.items():
                    self.cum_tracker.offer(int(acl), int(src), int(est))
            self.total_lines += meta["lines"]
            self.total_parsed += meta["parsed"]
            self.total_skipped += meta["skipped"]
            self.total_chunks += meta["chunks"]
            # the NEXT window opens here, BEFORE the (potentially slow)
            # publish + ring-checkpoint phase: a /health poll or reload
            # request arriving mid-rotation sees the new window id with
            # zero pushed lines, never the closed window's stale counters
            self.win_id += 1
            self._begin_window()
            self.windows_published += 1
            flightrec.cursor(
                windows_published=self.windows_published,
                wal_seq=int(self._wal_next),
            )
            obs.metric_event(
                "serve.window", id=meta["id"], lines=meta["lines"],
                chunks=meta["chunks"], drops=meta["drops"],
            )
            # host-tier hook: the distributed ingest worker overrides
            # this to ship the closed epoch to rank 0's merge plane
            # (runtime/distserve.py); the single-host service keeps
            # everything local.  AFTER local accounting, BEFORE the
            # (slow) publish phase, so the merge tier is never gated on
            # this host's disk
            self._emit_epoch(ep)
            # durable history spill (DESIGN §25): every rotation, not
            # just ring eviction — the store's frontier tracks
            # publication, so the ring eviction point merely marks when
            # the store becomes the ONLY copy
            self._spill_epoch(ep)
            if self._suffix is not None:
                self._suffix.push(meta["id"], arrays)
            self._publish(rep_obj, prev, meta)
            self._observe_slo(meta, win_hist)
            if (
                self.scfg.checkpoint_every_windows
                and self.windows_published % self.scfg.checkpoint_every_windows == 0
            ):
                self._save_ring_ckpt()

    #: lineage record kind this driver publishes (HostServeDriver says
    #: "host"; the distributed supervisor assembles "dist" records of
    #: its own in runtime/distserve.py)
    _lineage_kind = "window"

    def _assemble_lineage(self, meta: dict, quarantine: dict) -> dict:
        """The closed window's sealed provenance record (DESIGN §24).

        Everything except ``term``/``path``/``published_unix``/``crc``
        is a deterministic function of the delivered lines — the
        replay-identity law tests pin.
        """
        rec: dict = {
            "window": meta["id"],
            "kind": self._lineage_kind,
            "hosts": [{
                "rank": int(getattr(self, "rank", 0)),
                "wal_seq_lo": int(self._win_wal_lo),
                "wal_seq_hi": int(self._wal_next),
                "drops": int(meta.get("drops", 0)),
                "quarantine_hits": int(sum(quarantine.values())),
            }],
            "generation": int(self.reloads),
            "term": int(self.term),
            "path": self._path,
            "published_unix": round(time.time(), 3),
        }
        if meta.get("incomplete"):
            rec["incomplete"] = meta["incomplete"]
        return seal_lineage(rec)

    def _lineage_append(self, rec: dict) -> None:
        """Ledger a publication's lineage record — a CORE step.

        The jsonl append happens BEFORE the window file is written and
        lets failures propagate typed: a window must never publish
        without its provenance, and the single-write O_APPEND
        discipline means the ledger can never hold a torn record
        (chaos-pinned via the ``lineage.append`` site).
        """
        if self._lineage_log is not None:
            self._lineage_log.append(rec)
        with self._pub_lock:
            if rec.get("kind") == "merged":
                self._lineage_merged[rec["k"]] = rec
            else:
                self._lineage_recent[rec["window"]] = rec
                live = set(self.ring.window_ids())
                for wid in [
                    w for w in self._lineage_recent if w not in live
                ]:
                    del self._lineage_recent[wid]
        self.lineage_records_total += 1

    def lineage_tail(self) -> dict:
        """The ``/lineage`` HTTP view: ring-retained records."""
        with self._pub_lock:
            recs = [self._lineage_recent[w] for w in sorted(self._lineage_recent)]
            merged = [self._lineage_merged[k] for k in sorted(self._lineage_merged)]
        out = {
            "records": recs,
            "merged": merged,
            "records_total": self.lineage_records_total,
        }
        store = getattr(self, "epoch_store", None)
        if store is not None:
            # the durable-history frontier: a postmortem reading
            # /lineage can say exactly which windows survived the crash
            out["epoch_store"] = store.frontier()
        return out

    def lineage_record(self, wid: int) -> dict | None:
        with self._pub_lock:
            return self._lineage_recent.get(wid)

    def _observe_slo(self, meta: dict, hist=None) -> None:
        """Feed one published window to the burn-rate engine (--slo)."""
        if self.slo is None:
            return
        stats = window_slo_stats(
            hist if (hist is not None and hist.count) else None,
            lines=int(meta.get("lines", 0)),
            drops=int(meta.get("drops", 0)),
            incomplete=bool(meta.get("incomplete")),
            degraded=len(self.degraded_set()),
            window=meta.get("id"),
        )
        events = self.slo.observe(stats)
        for ev in events:
            # typed obs instant (reaches the flight ring via the armed
            # tap) + metrics-JSONL event: slo.breach / slo.recovered
            obs.typed_event(ev.pop("event"), **ev)
        if events:
            flightrec.cursor(
                slo_breached=sum(
                    1 for b in self.slo._breached.values() if b
                ),
            )

    def _emit_epoch(self, ep: WindowEpoch) -> None:
        """A closed window leaves the driver (no-op hook).

        ``serve --distributed`` host workers override this to hand the
        epoch — arrays, tracker tables, accounting meta, WAL cursor —
        to the cross-host merge tier.  The base service is its own merge
        tier (the ring push above already happened), so nothing to do.
        """

    @staticmethod
    def _rule_labels(packed) -> list[tuple]:
        """(firewall, acl, index) per key id — the epoch store's
        last-hit/trend planes need rule identity in the exact string
        space the static classes use."""
        return [(m.firewall, m.acl, m.index) for m in packed.key_meta]

    def _spill_epoch(self, ep: WindowEpoch) -> None:
        """Durably spill the closed window into the epoch store.

        A spill failure (the ``epochstore.spill`` site, or a real full/
        readonly volume) degrades the ``epoch_store`` subsystem and
        publication continues — history's frontier freezes visibly
        (/health, /lineage, gauges) and stays frozen: resuming spills
        mid-run would leave a window-id gap the store's dense numbering
        exists to prevent.
        """
        store = self.epoch_store
        if store is None or "epoch_store" in self.degraded_set():
            return
        try:
            store.spill(ep)
        except AnalysisError as e:
            self._degrade("epoch_store", e)
        else:
            flightrec.cursor(
                epochstore_window=int(ep.meta["id"]),
                epochstore_levels=len(store._chains),
            )

    def range_report_obj(self, frm: str | None, to: str | None) -> dict:
        """The ``/report/range`` answer: a full report rendered from
        <= 2*log2(n) stored aggregates — or the typed range_incomplete
        marker when the store cannot cover the span completely."""
        store = self.epoch_store
        if store is None:
            return {"error": "epoch store not armed (serve --epoch-store)"}
        t0 = time.monotonic()
        try:
            lo, hi = store.resolve_range(frm, to)
        except AnalysisError as e:
            return {"error": str(e)}
        agg, marker = store.range_agg(lo, hi)
        if marker is not None:
            return marker
        with self._pub_lock:
            packed = self.packed
        obj = self._attach_static(
            json.loads(render_range_report(
                agg, packed, self.cfg, topk=self.topk,
                v6_digests=self._v6_digests,
                window_extra={
                    "mode": "lines" if self.scfg.window_lines else "sec",
                    "length": (
                        self.scfg.window_lines or self.scfg.window_sec
                    ),
                },
            ).to_json()),
            strict=False,
        )
        self.lat_range.record(time.monotonic() - t0)
        return obj

    def _publish(self, rep_obj: dict, prev: dict | None, meta: dict) -> None:
        with obs.span("serve.publish", window=meta["id"]):
            # cumulative counters may span reloads: contradictions there
            # annotate rather than abort (attach docstring)
            cum_obj = self._attach_static(
                json.loads(self._render_cumulative().to_json()), strict=False
            )
            diff_obj = None
            if prev is not None:
                # window-over-window churn via the diff-reports machinery
                diff_obj = diff_report_objs(prev, rep_obj, top=self.topk)
                diff_obj["windows"] = [
                    prev["totals"].get("window", {}).get("id"),
                    meta["id"],
                ]
                if self.scfg.trend_threshold > 0:
                    # per-rule rate trends with hysteresis: an event only
                    # on label TRANSITION, so steady load emits nothing
                    evs = trend_events(
                        prev, rep_obj,
                        threshold=self.scfg.trend_threshold,
                        state=self._trend_state,
                    )
                    if evs:
                        diff_obj["trend_events"] = evs
                        self.trend_events_total += len(evs)
                        for ev in evs:
                            obs.typed_event(ev["event"], **{
                                k: v for k, v in ev.items() if k != "event"
                            })
            # lineage ledger append BEFORE the window file exists: a
            # window is never published without its provenance record
            lin = rep_obj.get("totals", {}).get("lineage")
            if lin is not None:
                self._lineage_append(lin)
            with self._pub_lock:
                self._published["report"] = rep_obj
                self._published["cumulative"] = cum_obj
                if diff_obj is not None:
                    self._published["diff"] = diff_obj
                self._window_reports[meta["id"]] = rep_obj
                # keep the in-memory per-window map bounded by the ring
                live = set(self.ring.window_ids())
                evicted = [w for w in self._window_reports if w not in live]
                for wid in evicted:
                    del self._window_reports[wid]
            # the ring is the retention policy on disk too: an always-on
            # service must not grow serve_dir one window file per
            # rotation forever (latest/cumulative/merged keep the
            # aggregate view; archive externally for longer history)
            for wid in evicted:
                for name in (f"window-{wid:06d}.json", f"diff-{wid:06d}.json"):
                    try:
                        os.remove(os.path.join(self.scfg.serve_dir, name))
                    except OSError:
                        pass
            self._write_json(f"window-{meta['id']:06d}.json", rep_obj)
            self._write_json("latest.json", rep_obj)
            self._write_json("cumulative.json", cum_obj)
            if diff_obj is not None:
                self._write_json(f"diff-{meta['id']:06d}.json", diff_obj)
            for k in self.scfg.views:
                eps = self.ring.last(k)
                if eps:
                    # serve-thread render: the serve thread is the only
                    # mutator of ring + packed, so no snapshot needed.
                    # The suffix cache answers the K-fold in O(1)
                    # amortized merges when its retained ids match the
                    # ring exactly; any mismatch (cold start, reload
                    # migration, resume) falls back to the full fold
                    cached = None
                    if self._suffix is not None:
                        cached = self._suffix.merged(
                            k, [ep.meta["id"] for ep in eps]
                        )
                    merged_obj = self._attach_static(
                        json.loads(
                            self._render_merged(
                                eps, self.packed, arrays=cached
                            ).to_json()
                        ),
                        strict=False,
                    )
                    if self.scfg.lineage:
                        # merged-K provenance: the parent-window links
                        # (in-memory + merged JSON only — the jsonl
                        # ledger stays one record per window)
                        mrec = seal_lineage({
                            "window": meta["id"],
                            "kind": "merged",
                            "k": k,
                            "parents": [
                                ep.meta["id"] for ep in eps
                            ],
                            "term": int(self.term),
                            "path": self._path,
                            "published_unix": round(time.time(), 3),
                        })
                        merged_obj["totals"]["lineage"] = mrec
                        with self._pub_lock:
                            self._lineage_merged[k] = mrec
                    self._write_json(f"merged-{k}.json", merged_obj)

    def _render_cumulative(self):
        # rendered only from _publish, AFTER _rotate merged the window's
        # quarantine into the cumulative bucket — no re-merge here
        q = self.cum_quarantine
        totals = {
            "lines_total": self.total_lines,
            "lines_matched": self.total_parsed,
            "lines_skipped": self.total_skipped,
            "chunks": self.total_chunks,
            "window": {
                "cumulative": True,
                "windows": self.windows_published,
                # restored history's drops + this process's: a resumed
                # service must not reset the loss magnitude its own
                # incomplete markers refer to
                "drops": self.drops_restored
                + int(self.queue.snapshot()["dropped"]),
            },
        }
        drops = self.drops_restored + int(self.queue.snapshot()["dropped"])
        reasons = list(self.cum_incomplete_reasons)
        if drops and "dropped_lines" not in reasons:
            reasons.append("dropped_lines")
        if drops or reasons:
            # any window lost traffic (drops, dead or stalled listener):
            # the cumulative view says so — its zero-hit rules are not
            # deletion evidence either
            totals["window"]["incomplete"] = {
                "drops": drops,
                "reasons": reasons,
                "windows": list(self.cum_incomplete_windows),
            }
        if self.lat_cum.count:
            # the service-lifetime SLO distribution (merged window
            # histograms — positional count addition, DESIGN §20)
            totals["latency"] = {"ingest_to_publish": self.lat_cum.summary()}
        qt = _quarantine_totals(q)
        if qt:
            totals["quarantine"] = qt
        deg = self.degraded_set()
        if deg:
            totals["degraded"] = deg
        return pipeline.finalize(
            pipeline.AnalysisState(**self.cum_arrays), self.packed, self.cfg,
            self.cum_tracker, topk=self.topk, totals=totals,
            v6_digests=self._v6_digests,
        )

    # -- ring checkpointing ----------------------------------------------
    def _save_ring_ckpt(self) -> None:
        arrays: dict[str, np.ndarray] = {}
        wmeta = []
        for ep in self.ring.epochs:
            pfx = f"w{ep.meta['id']:06d}__"
            for k, v in ep.arrays.items():
                arrays[pfx + k] = v
            wmeta.append({
                "meta": ep.meta,
                "tracker": [
                    [int(acl), [[int(s), int(e)] for s, e in t.items()]]
                    for acl, t in ep.tracker_tables.items()
                ],
                "quarantine": [
                    [fw, acl, idx, text, int(h)]
                    for (fw, acl, idx, text), h in sorted(ep.quarantine.items())
                ],
            })
        for k, v in self.cum_arrays.items():
            arrays["cum__" + k] = v
        snap = ckpt.Snapshot(
            arrays=arrays,
            lines_consumed=self.total_lines,
            n_chunks=self.total_chunks,
            parsed=self.total_parsed,
            skipped=self.total_skipped,
            tracker_tables=self.cum_tracker.tables(),
            fingerprint=self._fp,
            extra={
                "serve": {
                    # win_id is the already-open in-progress window (the
                    # rotation opened it before checkpointing); its
                    # partial lines are not in this snapshot, so a resume
                    # restarts it from empty under the same id
                    "next_window": self.win_id,
                    "windows_published": self.windows_published,
                    "windows": wmeta,
                    "reloads": self.reloads,
                    "quarantine": [
                        [fw, acl, idx, text, int(h)]
                        for (fw, acl, idx, text), h in sorted(
                            self.cum_quarantine.items()
                        )
                    ],
                    "v6_digests": [
                        [int(d), int(s)] for d, s in self._v6_digests.items()
                    ],
                    "incomplete_reasons": list(self.cum_incomplete_reasons),
                    "incomplete_windows": list(self.cum_incomplete_windows),
                    "drops": self.drops_restored
                    + int(self.queue.snapshot()["dropped"]),
                    # seq of the next line to consume: the WAL replay
                    # cursor a resume starts from (0 when the WAL is off
                    # — an off->on restart replays nothing, correctly)
                    "wal_seq": int(self._wal_next),
                    "wal_lost": int(self.wal_lost_total),
                }
            },
        )
        ckpt.save(self.scfg.checkpoint_dir or self._default_ckpt_dir(), snap)
        if self.wal is not None:
            # the checkpoint now covers every record below _wal_next:
            # make the spool durable, then release covered segments
            self.wal.sync()
            self.wal.gc(self._wal_next)

    def _default_ckpt_dir(self) -> str:
        return os.path.join(self.scfg.serve_dir, "ckpt")

    def _restore_ring(self) -> None:
        snap = ckpt.load(self.scfg.checkpoint_dir or self._default_ckpt_dir())
        if snap is None:
            return
        if snap.fingerprint != self._fp:
            raise ckpt.CheckpointMismatch(
                "serve checkpoint was taken with a different ruleset, "
                "sketch geometry, or mesh; refusing to resume the window "
                "ring (delete the serve checkpoint dir to start fresh)"
            )
        sv = (snap.extra or {}).get("serve")
        if not sv:
            raise ckpt.CheckpointCorrupt(
                "serve checkpoint manifest lacks the serve extra block"
            )
        self.total_lines = snap.lines_consumed
        self.total_chunks = snap.n_chunks
        self.total_parsed = snap.parsed
        self.total_skipped = snap.skipped
        self.cum_tracker = ckpt.restore_tracker(
            snap, self.cfg.sketch.topk_capacity
        )
        self.cum_arrays = {
            k[len("cum__"):]: v
            for k, v in snap.arrays.items()
            if k.startswith("cum__")
        }
        self.win_id = int(sv["next_window"])
        self.windows_published = int(sv.get("windows_published", 0))
        self.reloads = int(sv.get("reloads", 0))
        self.cum_quarantine = {
            (fw, acl, int(idx), text): int(h)
            for fw, acl, idx, text, h in sv.get("quarantine", [])
        }
        self._v6_digests.update(
            {int(d): int(s) for d, s in sv.get("v6_digests", [])}
        )
        self.cum_incomplete_reasons = list(sv.get("incomplete_reasons", []))
        self.cum_incomplete_windows = [
            int(w) for w in sv.get("incomplete_windows", [])
        ]
        self.drops_restored = int(sv.get("drops", 0))
        self._wal_resume_seq = int(sv.get("wal_seq", 0))
        self.wal_lost_total = int(sv.get("wal_lost", 0))
        for w in sv.get("windows", []):
            meta = w["meta"]
            pfx = f"w{meta['id']:06d}__"
            ep = WindowEpoch(
                arrays={
                    k[len(pfx):]: v
                    for k, v in snap.arrays.items()
                    if k.startswith(pfx)
                },
                meta=meta,
                tracker_tables={
                    int(acl): {int(s): int(e) for s, e in t}
                    for acl, t in w.get("tracker", [])
                },
                quarantine={
                    (fw, acl, int(idx), text): int(h)
                    for fw, acl, idx, text, h in w.get("quarantine", [])
                },
            )
            self.ring.push(ep)
        # repopulate the publication surface from the restored ring:
        # /report and /report/window/<id> must serve the checkpointed
        # history immediately, not 404 until the next rotation (and the
        # first post-resume diff runs against the pre-restart window)
        for ep in self.ring.epochs:
            self._window_reports[ep.meta["id"]] = self._render_window_obj(ep)
        if self.ring.epochs:
            self._published["report"] = self._window_reports[
                self.ring.epochs[-1].meta["id"]
            ]
            self._published["cumulative"] = self._attach_static(
                json.loads(self._render_cumulative().to_json()),
                strict=False,  # restored counters may predate the ruleset
            )

    # -- metrics-driven elastic autoscaling (DESIGN §13) -------------------
    def _maybe_autoscale(self) -> None:
        """Sample the canonical signals; decide and actuate when armed.

        Runs every loop iteration but only samples at the poll cadence.
        The signals come from the SAME gauges ``/metrics`` exports:
        pressure = listener queue occupancy (the device tier is behind
        the offered load), starvation = the serve loop drained the queue
        and consumed nothing since the last sample (capacity is idle).
        """
        now = time.monotonic()
        if now < self._as_next:
            return
        poll = self.ascfg.poll_sec if self.ascfg is not None else 1.0
        self._as_next = now + poll
        q = self.queue.snapshot()
        pressure = q["depth"] / q["capacity"]
        consumed = self.lines_consumed_total
        starved = 1.0 if (
            consumed == self._as_consumed_last and q["depth"] == 0
        ) else 0.0
        with self._gauge_lock:
            if self._as_last_t is not None:
                dt = now - self._as_last_t
                self._pressure_sec += pressure * dt
                self._starved_sec += starved * dt
                self._rate_inst = (
                    (consumed - self._as_consumed_last) / dt if dt > 0 else 0.0
                )
            self._as_last_t = now
            self._as_consumed_last = consumed
            self._last_pressure = pressure
            self._last_starved = starved
        if self._engine is None:
            return
        dec = self._engine.observe(
            now=now,
            pressure=pressure,
            starvation=starved,
            gauges={
                "queue_depth": q["depth"],
                "queue_capacity": q["capacity"],
                "lines_consumed_total": consumed,
                "world": self.world,
            },
        )
        if dec is not None and dec.actuate:
            self._apply_scale(dec)

    def _apply_scale(self, dec) -> None:
        """Re-form the serve mesh at the decided world (a planned event).

        No flush, no extra steps: the batcher and v6 staging are host
        state, and the replicated registers move device-to-device
        exactly — so chunk boundaries (and the full report, candidates
        included) are bit-identical to a fixed-world run over the same
        lines.  Only in-flight candidate outputs drain first (they are
        device arrays of the outgoing mesh).
        """
        import jax

        with obs.span(
            "autoscale.apply",
            seq=dec.seq, direction=dec.direction,
            from_world=dec.from_world, to_world=dec.to_world,
        ):
            # chaos seam: actuation failing must leave the old mesh
            # serving or abort typed — fire before any mutation
            faults.fire("autoscale.spawn")
            while self.pending:
                self._drain(self.pending.popleft())
            arrays = pipeline.state_to_host(self.state)
            k = dec.to_world
            mesh = self._mesh_lib.make_mesh(
                self._devices[:k], axis=self.cfg.mesh_axis
            )
            with self._pub_lock:  # /health reads world
                self.mesh = mesh
                self.world = k
            self._install_ruleset(self.packed)  # re-ship + rebuild steps
            self.state = pipeline.AnalysisState(**{
                name: jax.device_put(v, self._mesh_lib.replicated(mesh))
                for name, v in arrays.items()
            })
        self._engine.applied(dec, now=time.monotonic())
        obs.metric_event(
            "autoscale.applied",
            seq=dec.seq, world=k,
            time_to_effect_sec=dec.evidence.get("time_to_effect_sec"),
        )

    # -- hot reload -------------------------------------------------------
    def _maybe_reload(self) -> None:
        if not self._reload_req.is_set():
            return
        self._reload_req.clear()
        with obs.span("serve.reload"):
            try:
                self._do_reload()
            except _ReloadFlushError as e:
                raise e.__cause__  # step failure, not a reload failure
            except (AnalysisError, ValueError, OSError) as e:
                # atomic failure: nothing was swapped, the old tensor and
                # counters keep serving; the error is visible in /health
                self.reload_errors += 1
                self.last_reload_error = str(e)
                obs.instant("serve.reload.failed", args={"error": str(e)[:200]})

    def _do_reload(self) -> None:
        old_packed = self.packed
        new_packed = pack_mod.load_packed(self.prefix)
        # fault site FIRST: a reload that dies mid-swap must leave the
        # old tensor, registers, and in-flight batch completely intact
        faults.fire("reload.midbatch")
        mig = build_migration(old_packed, new_packed)
        # re-analyze the NEW ruleset before anything swaps: only changed
        # ACLs re-tile (signature reuse); a failure here — including the
        # analyze.tile fault site — is an atomic reload failure, so the
        # previous COMPLETE verdict set keeps serving
        sa_new = dur_new = None
        if self.scfg.static_analysis:
            sa_new, dur_new = self._compute_static(new_packed, reuse=self._sa)
        # step everything parsed under the OLD ruleset through the OLD
        # programs — gids/keys in flight belong to the old space
        try:
            self._flush_inflight()
        except Exception as e:
            raise _ReloadFlushError() from e
        # build everything the swap needs OFF the publish lock (device
        # shipping and jit lookup are the slow parts)
        dev_rules = pipeline.ship_ruleset(
            new_packed, match_impl=self.cfg.match_impl
        )
        step = self._make_step(new_packed)
        dev_rules6 = step6 = None
        if new_packed.has_v6:
            dev_rules6 = pipeline.ship_ruleset6(new_packed)
            step6 = self._make_step6(new_packed)
        from .stream import LineBatcher

        old_packer = self.batcher.packer
        packer = pack_mod.LinePacker(new_packed)
        packer.parsed, packer.skipped = old_packer.parsed, old_packer.skipped
        batcher = LineBatcher(
            packer, new_packed.has_v6, self._v6rows, self._v6_digests,
            self.batch_size,
        )
        new_state = None
        q: dict[tuple, int] = {}
        if not mig.identity:
            arrays = pipeline.state_to_host(self.state)
            new_arrays, q = migrate_arrays(arrays, mig, old_packed, self.cfg)
            import jax

            new_state = pipeline.AnalysisState(**{
                k: jax.device_put(v, self._mesh_lib.replicated(self.mesh))
                for k, v in new_arrays.items()
            })
        # ONE publish-locked swap: ring epochs, cumulative image, live
        # state, rule tensor, programs, batcher, AND the static verdict
        # table move to the new key space together — an HTTP render can
        # never pair migrated arrays with the old ruleset (or old
        # arrays / old verdicts with the new one).  The (O(R)) verdict
        # serialization happens off-lock, above.
        sa_obj_new = (
            sa_new.to_obj(new_packed) if sa_new is not None else None
        )
        with self._pub_lock:
            if not mig.identity:
                _merge_quarantine(self.win_quarantine, q)
                for ep in self.ring.epochs:
                    ep_arrays, ep_q = migrate_arrays(
                        ep.arrays, mig, old_packed, self.cfg
                    )
                    ep.arrays = ep_arrays
                    _merge_quarantine(ep.quarantine, ep_q)
                    ep.meta["migrated"] = ep.meta.get("migrated", 0) + 1
                    new_tables, dropped = migrate_tracker_tables(
                        ep.tracker_tables, mig
                    )
                    ep.tracker_tables = new_tables
                    self.talker_entries_dropped += dropped
                self.cum_arrays, cq = migrate_arrays(
                    self.cum_arrays, mig, old_packed, self.cfg
                )
                _merge_quarantine(self.cum_quarantine, cq)
                cum_tables, cdrop = migrate_tracker_tables(
                    self.cum_tracker.tables(), mig
                )
                self.talker_entries_dropped += cdrop
                self.cum_tracker = TopKTracker(self.cfg.sketch.topk_capacity)
                for acl, table in cum_tables.items():
                    for src, est in table.items():
                        self.cum_tracker.offer(acl, src, est)
                win_tables, wdrop = migrate_tracker_tables(
                    self.tracker.tables(), mig
                )
                self.talker_entries_dropped += wdrop
                self.tracker = TopKTracker(self.cfg.sketch.topk_capacity)
                for acl, table in win_tables.items():
                    for src, est in table.items():
                        self.tracker.offer(acl, src, est)
                self.state = new_state
            self.packed = new_packed
            self.dev_rules = dev_rules
            self.step = step
            self.dev_rules6 = dev_rules6
            self.step6 = step6
            self.batcher = batcher
            if sa_new is not None:
                self._install_static(sa_new, sa_obj_new, dur_new)
        if sa_new is not None:
            self._static_side_effects(sa_obj_new, dur_new)
        self._fp = self._fingerprint(new_packed)
        self.reloads += 1
        self.win_reloads += 1
        if not mig.identity:
            if self._suffix is not None:
                # the cached suffix merges are OLD-key-space images the
                # in-place ring migration above just invalidated
                self._suffix.invalidate()
            if self.epoch_store is not None:
                # windows >= the in-progress one live in the new key
                # space: the store refuses ranges reaching across (and
                # summary nodes never straddle the boundary)
                self.epoch_store.mark_era(self.win_id, self.reloads)
        if self.epoch_store is not None:
            self.epoch_store.set_labels(self._rule_labels(new_packed))
        obs.instant("serve.reload.ok", args={
            "n_keys": new_packed.n_keys,
            "migrated": not mig.identity,
        })

    # -- service plumbing -------------------------------------------------
    def _start_http(self) -> None:
        if self._http is None:  # bound in __init__; "off" leaves it None
            return
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, name="ra-serve-http", daemon=True
        )
        self._http_thread.start()

    def _start_watcher(self) -> None:
        if not self.scfg.reload_watch:
            return

        def watch():
            # debounced: save_packed writes TWO files (.npz + .json)
            # whose mtimes settle at different polls — fire ONE reload
            # once the pair has been stable for a full poll interval,
            # never per-file (a double reload is a wasted re-pack and a
            # half-written pair is a load failure)
            last = self._ruleset_mtimes()
            pending = None
            while not self._stop_req.wait(self.scfg.reload_poll_sec):
                cur = self._ruleset_mtimes()
                if cur == last:
                    pending = None
                    continue
                if any(m is None for m in cur):
                    continue  # file mid-replace; wait for the pair
                if cur == pending:  # stable across a whole poll: fire
                    last = cur
                    pending = None
                    self._reload_req.set()
                else:
                    pending = cur

        self._watch_thread = threading.Thread(
            target=watch, name="ra-serve-reload-watch", daemon=True
        )
        self._watch_thread.start()

    def _ruleset_mtimes(self) -> tuple:
        out = []
        for suffix in (".npz", ".json"):
            try:
                st = os.stat(self.prefix + suffix)
                out.append((st.st_mtime_ns, st.st_size))
            except OSError:
                out.append(None)
        return tuple(out)

    def _install_signals(self) -> None:
        import signal

        if threading.current_thread() is not threading.main_thread():
            return
        # SIGINT/SIGTERM request a GRACEFUL stop (the only way to stop a
        # --max-windows 0 service): the loop exits at its next check,
        # publishes the final partial window, and writes summary.json —
        # the default KeyboardInterrupt would skip both and lose the
        # open window's delivered lines from every report
        wanted = {
            getattr(signal, "SIGHUP", None): lambda *_: self._reload_req.set(),
            signal.SIGINT: lambda *_: self._stop_req.set(),
            signal.SIGTERM: lambda *_: self._stop_req.set(),
        }
        for sig, handler in wanted.items():
            if sig is None:
                continue
            try:
                self._old_signals[sig] = signal.signal(sig, handler)
            except (ValueError, OSError):
                pass

    def _teardown(self, aborted: BaseException | None) -> None:
        import signal

        self._stop_req.set()
        for sig, old in self._old_signals.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old_signals = {}
        if self._http is not None:
            if self._http_thread is not None:
                # shutdown() handshakes with serve_forever — calling it
                # when the serving thread never started blocks forever
                self._http.shutdown()
                self._http.server_close()
                self._http_thread.join(timeout=5.0)
            else:
                self._http.server_close()
        self.listeners.close()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5.0)
        if self.wal is not None:
            self.wal.close()
        if self.epoch_store is not None:
            self.epoch_store.sync()
            self.epoch_store.close()
        if self._lineage_log is not None:
            self._lineage_log.sync()
            self._lineage_log.close()
            self._lineage_log = None
        obs.unregister_sampler("listener")
        obs.unregister_sampler("serve")

    def _loop(self) -> None:
        scfg = self.scfg
        t0 = time.monotonic()
        next_rotation = (
            t0 + scfg.window_sec if scfg.window_sec else None
        )
        while True:
            if self._stop_req.is_set():
                break
            if scfg.stop_after_sec and time.monotonic() - t0 >= scfg.stop_after_sec:
                break
            self._maybe_reload()
            self._maybe_autoscale()
            self._check_metrics_health()
            # wall-clock rotation fires under load too, not just when idle
            if next_rotation is not None and time.monotonic() >= next_rotation:
                self._rotate()
                # skip cadence slots the rotation itself overran (the
                # fsync-bound ring checkpoint can take seconds): firing
                # them back-to-back would publish a burst of empty
                # windows that evicts every real epoch from the ring
                next_rotation += scfg.window_sec
                now = time.monotonic()
                while next_rotation <= now:
                    next_rotation += scfg.window_sec
                if scfg.max_windows and self.windows_published >= scfg.max_windows:
                    break
                continue
            got = self.queue.pop_ts(timeout=0.1)
            if got is not None:
                line, t_recv = got
                if self.wal is not None:
                    # durably spool BEFORE window accounting: once this
                    # returns, a SIGKILL cannot lose the line — resume
                    # replays it into the same window deterministically
                    self._wal_next = self.wal.append(line) + 1
                for ev in self.batcher.push(line):
                    self._consume_event(ev)
                self._note_receipt(t_recv)
                self.win_pushed += 1
                self.lines_consumed_total += 1
                # lines-mode rotation: deterministic, replayable windows
                if scfg.window_lines and self.win_pushed >= scfg.window_lines:
                    self._rotate()
                    if scfg.max_windows and self.windows_published >= scfg.max_windows:
                        break
                continue
            # idle tick: listener liveness
            if self.listeners.alive() == 0 and len(self.queue) == 0:
                err = self.listeners.first_error()
                if err is not None:
                    raise FeedWorkerError(
                        f"every serve listener died; first error: "
                        f"{type(err).__name__}: {err}"
                    ) from err
                break  # all ingress closed cleanly and drained: done
            # wedged-listener watchdog: a parked receive thread still
            # says is_alive(), but its heartbeat stops — overlapping
            # windows get the incomplete marker, and once EVERY live
            # listener is wedged with nothing queued the service aborts
            # typed instead of idling forever on traffic it cannot see
            stalled = self.listeners.stalled(self.cfg.stall_timeout_sec)
            if stalled:
                self._win_saw_stall = True
                if len(stalled) == self.listeners.alive() and len(self.queue) == 0:
                    names = ", ".join(ln.label for ln in stalled)
                    raise StallError(
                        f"every live serve listener stalled (no heartbeat "
                        f"for {self.cfg.stall_timeout_sec:g}s): {names}"
                    )
        # bounded shutdown: stop ingress FIRST, then account every line
        # still queued as an explicit drop — a stop request must not
        # analyze an unbounded backlog, and must never pretend the
        # backlog did not exist (the final window carries the incomplete
        # marker; summary.drops reports the loss)
        self.listeners.close()
        undelivered = self.queue.discard_remaining()
        # final partial window: publish (marked partial) rather than drop
        # consumed lines on the floor — unless it is empty
        if (
            self.win_pushed
            or self.batcher.raw
            or self._fill6
            or self.pending
            or self.win_lines
            or undelivered
        ):
            self._rotate(partial=True)


# ---------------------------------------------------------------------------
# Minimal loopback HTTP JSON endpoint.
# ---------------------------------------------------------------------------


def _make_http_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        server_version = "ra-serve/1"

        def log_message(self, *a):  # silence per-request stderr noise
            pass

        def _send(self, code: int, obj) -> None:
            body = json.dumps(obj, indent=2).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, text: str, ctype: str) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (http.server API)
            drv: ServeDriver = self.server.driver
            raw_path, _, query = self.path.partition("?")
            path = raw_path.rstrip("/") or "/"
            try:
                if path == "/health":
                    return self._send(200, drv.health())
                if path == "/metrics":
                    if "format=prom" in query:
                        # Prometheus text exposition of the SAME gauges
                        # the autoscale policy consumes (one source of
                        # truth; version 0.0.4 text format)
                        return self._send_text(
                            200,
                            render_prom(
                                drv.metrics_gauges(), prefix="ra_serve_"
                            )
                            + drv.render_latency_prom()
                            + drv.render_labeled_prom(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    return self._send(
                        200, {
                            **drv._sample_metrics(),
                            **drv.metrics_gauges(),
                            "build_info": drv.build_info_dict(),
                        }
                    )
                if path == "/report":
                    obj = drv.published("report")
                    return self._send(200, obj) if obj else self._send(
                        404, {"error": "no window published yet"}
                    )
                if path == "/report/cumulative":
                    obj = drv.published("cumulative")
                    return self._send(200, obj) if obj else self._send(
                        404, {"error": "no window published yet"}
                    )
                if path == "/report/static":
                    obj = drv.published("static")
                    return self._send(200, obj) if obj else self._send(
                        404,
                        {"error": "static analysis disabled "
                                  "(serve --static-analysis) or not yet run"},
                    )
                if path == "/diff":
                    obj = drv.published("diff")
                    return self._send(200, obj) if obj else self._send(
                        404, {"error": "fewer than two windows published"}
                    )
                if path.startswith("/report/window/"):
                    try:
                        wid = int(path.rsplit("/", 1)[1])
                    except ValueError:
                        return self._send(400, {"error": "bad window id"})
                    obj = drv.window_report(wid)
                    return self._send(200, obj) if obj else self._send(
                        404, {"error": f"window {wid} not in the ring"}
                    )
                if path.startswith("/report/merged/"):
                    try:
                        k = int(path.rsplit("/", 1)[1])
                    except ValueError:
                        return self._send(400, {"error": "bad window count"})
                    if not 1 <= k <= drv.scfg.ring:
                        # the refuse-don't-shrink rule ServeConfig
                        # applies to --view: a merged-24 answer from an
                        # 8-epoch ring would claim 24 windows of
                        # evidence while holding 8
                        return self._send(400, {
                            "error": (
                                f"merged window count must be in "
                                f"1..{drv.scfg.ring} (the ring size), "
                                f"got {k}; raise --ring to retain more"
                            ),
                        })
                    obj = drv.merged_report_obj(k)
                    return self._send(200, obj) if obj else self._send(
                        404, {"error": "no windows in the ring"}
                    )
                if path == "/report/range":
                    # historical [t0,t1] analytics (DESIGN §25): bounds
                    # are window ids or unix seconds; the answer is a
                    # full report (O(log n) stored aggregates), a typed
                    # range_incomplete marker, or a 400 on bad bounds
                    from urllib.parse import parse_qs

                    params = parse_qs(query)
                    obj = drv.range_report_obj(
                        (params.get("from") or [None])[0],
                        (params.get("to") or [None])[0],
                    )
                    if "error" in obj:
                        code = 404 if "not armed" in obj["error"] else 400
                        return self._send(code, obj)
                    return self._send(
                        404 if obj.get("range_incomplete") else 200, obj
                    )
                if path == "/report/last-hit":
                    store = getattr(drv, "epoch_store", None)
                    if store is None:
                        return self._send(404, {
                            "error": "epoch store not armed "
                                     "(serve --epoch-store)",
                        })
                    return self._send(200, store.last_hit_obj())
                if path == "/lineage":
                    if not drv.scfg.lineage:
                        return self._send(404, {
                            "error": "lineage disabled (--lineage off)",
                        })
                    return self._send(200, drv.lineage_tail())
                if path.startswith("/lineage/window/"):
                    try:
                        wid = int(path.rsplit("/", 1)[1])
                    except ValueError:
                        return self._send(400, {"error": "bad window id"})
                    obj = drv.lineage_record(wid)
                    return self._send(200, obj) if obj else self._send(
                        404, {
                            "error": f"no lineage for window {wid} in the "
                            "ring (the full history is lineage.jsonl in "
                            "the serve dir)",
                        }
                    )
                return self._send(404, {
                    "error": "unknown path",
                    "endpoints": [
                        "/health", "/metrics", "/report",
                        "/report/cumulative", "/report/static",
                        "/report/window/<id>", "/report/merged/<k>",
                        "/report/range?from=&to=", "/report/last-hit",
                        "/diff", "/lineage", "/lineage/window/<id>",
                    ],
                })
            except BrokenPipeError:
                pass

    return Handler


def _make_http_server(addr, driver):
    from http.server import ThreadingHTTPServer

    srv = ThreadingHTTPServer(addr, _make_http_handler())
    srv.daemon_threads = True
    srv.driver = driver
    return srv


def window_incomplete(report_obj: dict) -> dict | None:
    """The typed WindowIncomplete marker of a serve report, or None.

    Consumers (operators, tests, downstream diff tooling) use this to
    refuse treating an incomplete window's zero-hit rules as unused.
    """
    return (report_obj.get("totals", {}).get("window") or {}).get("incomplete")
