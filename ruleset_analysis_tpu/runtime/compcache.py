"""Persistent XLA compilation cache.

The analysis step compiles once per (mesh, batch geometry, sketch
geometry); a fresh process pays that compile again (~15s on the TPU
tunnel) unless the persistent cache is on.  Entry points (CLI, bench.py,
bench_suite.py) call :func:`enable_persistent_cache` before first
compile; libraries never touch global JAX config themselves.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "ruleset_analysis_tpu", "xla_cache"
)


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX at an on-disk compilation cache; return the dir (or None).

    Safe to call multiple times and before/after jax import; failures
    (read-only filesystem, old jax) degrade to no caching rather than
    erroring — the cache is an optimization, never a requirement.
    """
    path = cache_dir or os.environ.get("RA_XLA_CACHE_DIR") or _DEFAULT_DIR
    platforms = os.environ.get("JAX_PLATFORMS", "default") or "default"
    # CPU-only runs (the dev/test fallback) skip the persistent cache by
    # default: XLA:CPU re-loads its AOT result with pseudo machine
    # features (+prefer-no-scatter, ...) and emits a scary
    # possible-SIGILL error log on every cache hit — and on some jaxlib
    # builds the reloaded executable computes WRONG values (observed:
    # corrupted HLL registers when test workers shared a cache dir).
    # RA_XLA_CACHE_DIR forces it on anyway, at the caller's own risk.
    # TPU runs — where the ~15s step compile actually hurts — always
    # cache.
    if platforms == "cpu" and not os.environ.get("RA_XLA_CACHE_DIR"):
        return None
    # namespace by backend selection so axon/tpu and cpu runs never share
    # entries compiled for a different executor
    path = os.path.join(path, platforms.replace(",", "+"))
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache even fast compiles: the step compiles in ~1s on CPU but
        # the suite builds dozens of fresh jit wrappers per run
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # JAX-executable entries only: XLA:CPU's AOT sub-caches re-load
        # with machine-feature pseudo-flags (+prefer-no-scatter, ...) that
        # trip a "could SIGILL" error log on every cache hit
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
        return path
    except Exception:
        return None
