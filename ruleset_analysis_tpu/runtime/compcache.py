"""Persistent XLA compilation cache.

The analysis step compiles once per (mesh, batch geometry, sketch
geometry); a fresh process pays that compile again (~15s on the TPU
tunnel) unless the persistent cache is on.  Entry points (CLI, bench.py,
bench_suite.py) call :func:`enable_persistent_cache` before first
compile; libraries never touch global JAX config themselves.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "ruleset_analysis_tpu", "xla_cache"
)


def enable_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX at an on-disk compilation cache; return the dir (or None).

    Safe to call multiple times and before/after jax import; failures
    (read-only filesystem, old jax) degrade to no caching rather than
    erroring — the cache is an optimization, never a requirement.
    """
    path = cache_dir or os.environ.get("RA_XLA_CACHE_DIR") or _DEFAULT_DIR
    # namespace by backend selection: axon/tpu and cpu-fallback runs must
    # not share AOT entries (XLA:CPU loads cached code compiled with
    # different machine-feature sets and warns of possible SIGILL)
    platforms = os.environ.get("JAX_PLATFORMS", "default") or "default"
    path = os.path.join(path, platforms.replace(",", "+"))
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache even fast compiles: the step compiles in ~1s on CPU but
        # the suite builds dozens of fresh jit wrappers per run
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        return path
    except Exception:
        return None
