"""Metrics-driven elastic autoscaling: the load-reactive policy engine.

PR 1's elastic tier reacts to *death* (a peer drops, the cluster
re-forms smaller); production traffic is bursty, so this module makes
the same machinery react to *load* (ROADMAP item 4).  The metrics plane
(runtime/obs.py) already exports exactly the signals a scaling policy
needs — producer backpressure seconds (the device tier cannot keep up),
consumer starvation seconds (capacity sits idle), queue depth, lines/s —
and the epoch-tagged world-size-independent checkpoints were designed so
ANY world size can resume them.  Autoscaling is therefore a *policy*
problem, not a new mechanism: decide when the signals justify a
different world size, then drive the existing re-formation machinery as
a planned scale event.

Three pieces:

- :class:`PolicyEngine` — the pure decision core, unit-testable with
  synthetic samples.  Two canonical signals in [0, 1] per sample:
  **pressure** (device-bound fraction of recent wall time: sustained ⇒
  scale OUT) and **starvation** (input-bound idle fraction: sustained ⇒
  scale IN).  A decision needs the signal's *minimum* over a full
  ``sustain_sec`` window above threshold, at least ``cooldown_sec``
  since the previous decision, and budget left — the flap-damping math
  DESIGN §13 spells out.  Every decision carries its evidence (the
  window statistics + the raw gauges) and is an obs instant + metrics
  event; the ``autoscale.decide`` fault site fires right before a
  decision is returned so chaos schedules can land failures exactly at
  the decide→actuate seam.

- :class:`MetricsTail` + :func:`ingest_signals` — adapters from the live
  metrics JSONL stream (the one ``--metrics-out`` writes and external
  scrapers read: one source of truth) to the canonical signals.

- :class:`AutoscaleController` — the distributed actuation half: a
  thread the elastic *leader* supervisor runs per generation, tailing
  its rank-0 worker's metrics shard and publishing one scale request
  into the rendezvous directory when the engine decides (the supervisors
  then retire the generation at a checkpoint-bounded cost and re-form at
  the target world; runtime/elastic.py).  The serve driver embeds the
  engine directly (runtime/serve.py) and resizes its own device mesh.

Scale events are *planned*: they consume the autoscaler's own
``reform_budget``, never ``--max-reforms`` (which stays the failure
budget), and a budget of 0 runs the whole policy in observe-only mode —
decisions with evidence, no actuation — for drills and rollout.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque

from ..config import AutoscaleConfig
from ..errors import AnalysisError
from . import faults, obs


def parse_plan(plan: str) -> list[tuple[str, float]]:
    """``"out@T,in@T"`` -> ordered [(direction, seconds-offset)] entries.

    Validated by ``AutoscaleConfig.__post_init__``; this is the single
    decoder the engine uses (scripted drills/tests — production decides
    from the live signals).
    """
    out: list[tuple[str, float]] = []
    for part in filter(None, (p.strip() for p in plan.split(","))):
        d, _, t = part.partition("@")
        if d not in ("out", "in"):
            raise AnalysisError(
                f"autoscale plan entry {part!r}: direction must be 'out' or 'in'"
            )
        try:
            out.append((d, float(t)))
        except ValueError as e:
            raise AnalysisError(
                f"autoscale plan entry {part!r}: want DIRECTION@SECONDS"
            ) from e
    return out


def world_ladder(min_world: int, max_world: int, *, divisors_of: int = 0) -> list[int]:
    """Allowed world sizes, smallest first.

    ``divisors_of`` restricts the ladder to divisors of that extent —
    the serve driver's constraint: its padded batch geometry is fixed at
    the maximum world, and a world that divides it keeps every chunk
    boundary (and therefore the full report, candidates included)
    bit-identical across scale events.  0 = every integer in range (the
    elastic tier: the collective step is shape-correct at any world).
    """
    if divisors_of:
        rungs = [
            k for k in range(1, divisors_of + 1)
            if divisors_of % k == 0 and min_world <= k <= max_world
        ]
    else:
        rungs = list(range(min_world, max_world + 1))
    if not rungs:
        raise AnalysisError(
            f"autoscale world ladder is empty (min {min_world}, max "
            f"{max_world}" + (f", divisors of {divisors_of}" if divisors_of else "")
            + ")"
        )
    return rungs


def host_ladder(min_hosts: int, max_hosts: int) -> list[int]:
    """Allowed HOST counts of the distributed serve tier (DESIGN §22).

    The device-tier ladder restricts worlds to divisors of the padded
    batch geometry; the host tier has no such constraint — every host
    runs its own full (flat) mesh and the cross-host register merge is
    world-size-independent (the ``_merge_tail`` laws are associative),
    so any contiguous rung count is reachable.  The checkpoint
    fingerprint pins ``max_hosts`` (the ladder maximum), which is what
    lets a merged-ring checkpoint taken at any host count resume at any
    other on the same ladder.
    """
    return world_ladder(min_hosts, max_hosts)


@dataclasses.dataclass
class ScaleDecision:
    """One policy decision, evidence attached (obs + report facing)."""

    seq: int
    direction: str  # "out" | "in"
    from_world: int
    to_world: int
    reason: str  # "backpressure" | "starvation" | "plan"
    t: float  # engine clock (caller's ``now``) at decision time
    actuate: bool  # False in observe-only mode (reform_budget 0)
    evidence: dict

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class PolicyEngine:
    """Sustained-signal decision core (pure; feed it samples, get events).

    Decision table, evaluated at every :meth:`observe` (DESIGN §13):

    1. budget gone (``reform_budget`` actuations used) -> hold forever;
    2. within ``cooldown_sec`` of the previous decision -> hold;
    3. the sample window does not yet span ``sustain_sec`` -> hold
       (the window resets after every decision: post-reform signals
       describe a different capacity);
    4. min(pressure) over the window >= ``out_threshold`` and a higher
       rung exists -> scale OUT one rung;
    5. else min(starvation) >= ``in_threshold`` and a lower rung
       exists -> scale IN one rung.

    A reversal (out after in, or in after out) within
    ``2 * (cooldown_sec + sustain_sec)`` of the previous decision counts
    as a **flap** — the damping knobs exist to keep that number at zero,
    and the bench artifact asserts it.
    """

    def __init__(self, acfg: AutoscaleConfig, *, world: int, ladder: list[int]):
        if world not in ladder:
            raise AnalysisError(
                f"current world {world} is not on the autoscale ladder {ladder}"
            )
        self.acfg = acfg
        self.ladder = list(ladder)
        self.world = world
        self.budget_left = acfg.reform_budget
        self.observe_only = acfg.reform_budget == 0
        self.decisions: list[ScaleDecision] = []
        self.flaps = 0
        self.suppressed_budget = 0  # would-be decisions after budget ran out
        self._window: deque[tuple[float, float, float]] = deque()
        self._t0: float | None = None
        self._last: ScaleDecision | None = None
        self._plan = parse_plan(acfg.plan)
        self._plan_fired = 0
        self._budget_noted = False
        self._seq = 0

    # -- internals --------------------------------------------------------
    def _rung(self, direction: str) -> int | None:
        i = self.ladder.index(self.world)
        if direction == "out":
            return self.ladder[i + 1] if i + 1 < len(self.ladder) else None
        return self.ladder[i - 1] if i > 0 else None

    def _decide(
        self, direction: str, reason: str, now: float, evidence: dict
    ) -> ScaleDecision | None:
        target = self._rung(direction)
        if target is None:
            return None  # already at the edge of the ladder
        if not self.observe_only and self.budget_left <= 0:
            self.suppressed_budget += 1
            if not self._budget_noted:
                self._budget_noted = True
                obs.instant(
                    "autoscale.budget_exhausted",
                    args={"reform_budget": self.acfg.reform_budget},
                )
            return None
        prev = self._last
        if (
            prev is not None
            and prev.direction != direction
            and now - prev.t < 2 * (self.acfg.cooldown_sec + self.acfg.sustain_sec)
        ):
            self.flaps += 1
        self._seq += 1
        dec = ScaleDecision(
            seq=self._seq,
            direction=direction,
            from_world=self.world,
            to_world=target,
            reason=reason,
            t=now,
            actuate=not self.observe_only,
            evidence=evidence,
        )
        # chaos seam: a decision that fails to LEAVE the policy engine
        # must be a typed abort, never a half-issued scale event
        faults.fire("autoscale.decide")
        self.decisions.append(dec)
        self._last = dec
        self._window.clear()
        if dec.actuate:
            self.budget_left -= 1
            self.world = target
        # the damping window rides the instant so the trace alone can
        # count flaps (tools/trace_summary.py autoscale block)
        obs.instant(
            "autoscale.decide",
            args={
                **dec.to_dict(),
                "damping_window_sec": 2 * (self.acfg.cooldown_sec + self.acfg.sustain_sec),
            },
        )
        obs.metric_event("autoscale", **dec.to_dict())
        return dec

    # -- the sampling surface ---------------------------------------------
    def observe(
        self,
        *,
        now: float,
        pressure: float,
        starvation: float,
        gauges: dict | None = None,
    ) -> ScaleDecision | None:
        """Feed one sample; returns a decision when the table fires."""
        a = self.acfg
        if self._t0 is None:
            self._t0 = now
        pressure = min(max(float(pressure), 0.0), 1.0)
        starvation = min(max(float(starvation), 0.0), 1.0)
        self._window.append((now, pressure, starvation))
        while self._window and now - self._window[0][0] > a.sustain_sec * 1.5:
            self._window.popleft()

        if self._plan:
            # scripted drill: entries fire in order at their offsets,
            # bypassing thresholds and cooldown (the script IS the policy)
            if self._plan_fired < len(self._plan):
                d, t_off = self._plan[self._plan_fired]
                if now - self._t0 >= t_off:
                    self._plan_fired += 1
                    return self._decide(
                        d, "plan", now,
                        {
                            "plan_entry": f"{d}@{t_off:g}",
                            "pressure_last": pressure,
                            "starvation_last": starvation,
                            **({"gauges": gauges} if gauges else {}),
                        },
                    )
            return None

        if self._last is not None and now - self._last.t < a.cooldown_sec:
            return None
        if not self._window or now - self._window[0][0] < a.sustain_sec:
            return None  # window does not span the sustain bound yet
        ps = [p for _, p, _ in self._window]
        ss = [s for _, _, s in self._window]
        evidence = {
            "window_sec": round(now - self._window[0][0], 3),
            "samples": len(self._window),
            "pressure": {
                "min": round(min(ps), 4),
                "mean": round(sum(ps) / len(ps), 4),
                "last": round(pressure, 4),
                "threshold": a.out_threshold,
            },
            "starvation": {
                "min": round(min(ss), 4),
                "mean": round(sum(ss) / len(ss), 4),
                "last": round(starvation, 4),
                "threshold": a.in_threshold,
            },
            **({"gauges": gauges} if gauges else {}),
        }
        if min(ps) >= a.out_threshold:
            return self._decide("out", "backpressure", now, evidence)
        if min(ss) >= a.in_threshold:
            return self._decide("in", "starvation", now, evidence)
        return None

    def applied(self, dec: ScaleDecision, *, now: float) -> None:
        """Note actuation completed (time-to-effect lands in the summary)."""
        dec.evidence["time_to_effect_sec"] = round(now - dec.t, 3)

    def summary(self) -> dict:
        """Report/summary totals block ({} when nothing ever happened)."""
        if not self.decisions and not self.suppressed_budget:
            return {}
        return {
            "world": self.world,
            "decisions": [d.to_dict() for d in self.decisions],
            "scale_out": sum(1 for d in self.decisions if d.direction == "out"),
            "scale_in": sum(1 for d in self.decisions if d.direction == "in"),
            "flaps": self.flaps,
            "budget_left": self.budget_left,
            "observe_only": self.observe_only,
            **(
                {"suppressed_by_budget": self.suppressed_budget}
                if self.suppressed_budget
                else {}
            ),
        }


# ---------------------------------------------------------------------------
# Metrics-stream adapters: the JSONL the metrics plane writes (and
# external scrapers read) is the policy's one source of truth.
# ---------------------------------------------------------------------------


class MetricsTail:
    """Incremental reader of a metrics JSONL file another process writes.

    Tolerates the file not existing yet (the worker has not armed its
    metrics plane) and a torn final line (killed mid-write): bytes past
    the last newline stay unconsumed until completed.
    """

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = b""

    def poll(self) -> list[dict]:
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                chunk = f.read()
        except OSError:
            return []
        self._pos += len(chunk)
        self._buf += chunk
        recs: list[dict] = []
        while True:
            nl = self._buf.find(b"\n")
            if nl < 0:
                break
            line, self._buf = self._buf[:nl], self._buf[nl + 1:]
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn or foreign line: skip, keep tailing
            if isinstance(rec, dict):
                recs.append(rec)
        return recs


def ingest_signals(prev: dict | None, rec: dict) -> tuple[float, float] | None:
    """(pressure, starvation) from two consecutive metrics snapshots.

    The ingest sampler (runtime/ingest.py) exports *cumulative*
    backpressure/starvation seconds; the canonical signals are their
    derivative over the snapshot interval — the fraction of recent wall
    time the pipeline spent device-bound vs input-bound.  None when the
    pair cannot be differentiated yet (first snapshot, no ingest gauge,
    clock went backwards).
    """
    if prev is None:
        return None
    ing, ping = rec.get("ingest"), prev.get("ingest")
    if not isinstance(ing, dict) or not isinstance(ping, dict):
        return None
    try:
        dt = float(rec["t"]) - float(prev["t"])
        if dt <= 0:
            return None
        dp = float(ing["backpressure_sec"]) - float(ping["backpressure_sec"])
        ds = float(ing["starved_sec"]) - float(ping["starved_sec"])
    except (KeyError, TypeError, ValueError):
        return None
    clamp = lambda v: min(max(v / dt, 0.0), 1.0)  # noqa: E731
    return clamp(dp), clamp(ds)


def render_prom(gauges: dict, *, prefix: str = "ra_") -> str:
    """Prometheus text exposition of a flat numeric gauge dict.

    The serve ``/metrics?format=prom`` variant: the SAME gauges the
    policy engine consumes, so an external scraper and the autoscaler
    can never disagree about what the service saw.  Non-numeric values
    are skipped (the JSON variant keeps them); booleans export as 0/1.
    """
    lines: list[str] = []
    for key in sorted(gauges):
        v = gauges[key]
        if isinstance(v, bool):
            v = int(v)
        if not isinstance(v, (int, float)):
            continue
        name = prefix + "".join(c if c.isalnum() else "_" for c in str(key))
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {v:g}" if isinstance(v, float) else f"{name} {v}")
    return "\n".join(lines) + "\n"


def render_prom_labeled(
    per_label: dict[str, dict],
    *,
    prefix: str = "ra_",
    label: str = "tenant",
) -> str:
    """Labeled twin of :func:`render_prom` for per-tenant gauge families.

    ``per_label`` maps one label value (tenant name) to that tenant's
    flat numeric gauge dict; every gauge key becomes ONE metric family
    with one ``{label="value"}`` series per tenant — so a scraper sums
    or compares tenants without string-parsing metric names.  Same
    skip-non-numeric / bool-as-int rules as the flat rendering; the
    labeled drift audit (verify/registry.py) holds both renderings to
    the same JSON source.
    """
    families: dict[str, list[str]] = {}
    for value in sorted(per_label):
        for key in sorted(per_label[value]):
            v = per_label[value][key]
            if isinstance(v, bool):
                v = int(v)
            if not isinstance(v, (int, float)):
                continue
            name = prefix + "".join(c if c.isalnum() else "_" for c in str(key))
            body = f"{v:g}" if isinstance(v, float) else f"{v}"
            families.setdefault(name, []).append(
                f'{name}{{{label}="{value}"}} {body}'
            )
    lines: list[str] = []
    for name in sorted(families):
        lines.append(f"# TYPE {name} gauge")
        lines.extend(families[name])
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Elastic actuation: the leader supervisor's per-generation controller.
# ---------------------------------------------------------------------------


class AutoscaleController(threading.Thread):
    """Tail the rank-0 worker's metrics shard; publish ONE scale request.

    Runs on the elastic *leader* supervisor for the lifetime of one
    generation (runtime/elastic.py starts it after spawning the worker
    and stops it when the generation ends).  When the policy engine
    decides, the controller appends the decision to ``scale-log.jsonl``
    (the run's full decision history, report-facing) and atomically
    publishes ``scale.json`` (seq + target world) — every supervisor
    polls that file and retires its worker, which is the planned scale
    event.  One request per controller: the re-formation it causes
    replaces this generation (and this controller) anyway.
    """

    def __init__(
        self,
        acfg: AutoscaleConfig,
        *,
        world: int,
        ladder: list[int],
        metrics_path: str,
        publish,  # callable(ScaleDecision) -> None, actuated decisions
        budget_left: int,
        cooldown_anchor: float | None = None,
        log=None,  # callable(ScaleDecision) -> None, EVERY decision
    ):
        super().__init__(daemon=True, name="ra-autoscale")
        self.engine = PolicyEngine(acfg, world=world, ladder=ladder)
        # budget/cooldown survive across generations (each gets a fresh
        # controller): the supervisor passes what previous requests used
        self.engine.budget_left = max(
            0, min(self.engine.budget_left, budget_left)
        ) if not self.engine.observe_only else 0
        self._cooldown_anchor = cooldown_anchor
        self.acfg = acfg
        self._tail = MetricsTail(metrics_path)
        self._publish = publish
        self._log = log
        # NOT named _stop: threading.Thread.join() calls its internal
        # self._stop() after the thread exits, and an Event attribute of
        # that name shadows it
        self._stop_ev = threading.Event()
        self.decision: ScaleDecision | None = None
        self.error: BaseException | None = None

    def stop(self) -> None:
        self._stop_ev.set()

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:  # surfaced by the supervisor's join
            self.error = e

    def _run(self) -> None:
        a = self.acfg
        prev: dict | None = None
        # Differentiate over at least this stride: the ingest counters
        # advance in per-batch steps (a blocked put books its whole
        # blocked interval at once), so consecutive fine-grained
        # snapshots alternate between 0 and >1 fractions and the
        # engine's min-over-window would never cross a threshold.  A
        # ~1s stride averages over the batch cadence while staying well
        # inside any realistic sustain window.
        smooth = max(a.poll_sec, 1.0)
        if self._cooldown_anchor is not None:
            # seed the cooldown: a request published by the PREVIOUS
            # generation's controller still paces this one
            self.engine._last = ScaleDecision(
                seq=0, direction="", from_world=self.engine.world,
                to_world=self.engine.world, reason="carryover",
                t=self._cooldown_anchor, actuate=False, evidence={},
            )
        while not self._stop_ev.wait(min(a.poll_sec, 0.2)):
            now = time.monotonic()
            dec = None
            if self.engine._plan:
                # scripted drills pace on the controller clock even when
                # no snapshot has landed yet
                dec = self.engine.observe(now=now, pressure=0.0, starvation=0.0)
            for rec in self._tail.poll():
                if dec is not None:
                    break
                if rec.get("kind") not in ("snapshot", "final"):
                    continue
                if prev is not None and (
                    float(rec.get("t", 0)) - float(prev.get("t", 0)) < smooth
                ):
                    continue  # hold the anchor until a full stride passed
                sig = ingest_signals(prev, rec)
                prev = rec
                if sig is None:
                    continue
                pressure, starvation = sig
                dec = self.engine.observe(
                    now=now,
                    pressure=pressure,
                    starvation=starvation,
                    gauges={
                        "lines": rec.get("lines"),
                        "lines_per_sec_inst": rec.get("lines_per_sec_inst"),
                        "queue_depth": (rec.get("ingest") or {}).get("queue_depth"),
                    },
                )
            if dec is not None:
                if self._log is not None:
                    # EVERY decision lands in the run's decision log —
                    # observe-only mode (budget 0) exists precisely to
                    # produce this evidence without actuating
                    self._log(dec)
                if dec.actuate:
                    self.decision = dec
                    self._publish(dec)
                    return  # the generation is about to be retired


def flap_count(
    decisions: list[dict], *, cooldown_sec: float, sustain_sec: float
) -> int:
    """Flaps in a decision log: direction reversals inside the damping
    window ``2 * (cooldown_sec + sustain_sec)`` (DESIGN §13).

    Works on wall-clock ``t_wall`` stamps so it composes across
    generations and processes (the engine's own per-generation counter
    cannot see a reversal that spans a re-formation)."""
    window = 2 * (cooldown_sec + sustain_sec)
    flaps = 0
    prev: dict | None = None
    for d in decisions:
        if prev is not None and d.get("direction") != prev.get("direction"):
            t0, t1 = prev.get("t_wall"), d.get("t_wall")
            if (
                isinstance(t0, (int, float))
                and isinstance(t1, (int, float))
                and t1 - t0 < window
            ):
                flaps += 1
        prev = d
    return flaps


def append_decision_log(path: str, dec: ScaleDecision, **extra) -> None:
    """Append one decision to the run's scale-log.jsonl (crash-tolerant)."""
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps({**dec.to_dict(), **extra}, separators=(",", ":")) + "\n")


def read_decision_log(path: str) -> list[dict]:
    out: list[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        return out
    return out
