"""Framework error types (jax-free so the CLI can import them cheaply)."""


class AnalysisError(RuntimeError):
    """Base class for user-facing runtime errors."""


class CheckpointMismatch(AnalysisError):
    """Snapshot belongs to a different ruleset or sketch geometry."""


class CheckpointCorrupt(AnalysisError):
    """The pointed-to snapshot exists but cannot be decoded.

    Raised LOUDLY instead of silently starting the analysis from scratch:
    a truncated/bit-flipped snapshot usually means storage trouble, and a
    fresh-start would discard the operator's resume intent without a
    trace.  Recovery: delete the snapshot directory (or fix the storage)
    and rerun."""


class ResumeInputMismatch(AnalysisError):
    """Input stream is shorter than the snapshot's consumed-line offset."""


class NativeParserUnavailable(AnalysisError):
    """The C++ parser was requested but its library cannot be built/loaded."""


class FeedWorkerError(AnalysisError):
    """A parse feed worker (process or thread) died or reported failure.

    Raised by the multi-worker feed tiers instead of hanging on a
    completion that will never arrive — a worker killed by the OS (OOM),
    a crashed parse, or a poisoned descriptor all surface as this typed
    error within the liveness timeout."""


class IngestError(AnalysisError):
    """The prefetch producer failed with an untyped exception.

    The pipelined ingest engine re-raises producer-side failures at the
    consumer's next pull; failures that are not already AnalysisError
    subclasses are wrapped in this so the chaos invariant — every failed
    run exits with a TYPED error — holds for arbitrary producer bugs
    (the original exception rides ``__cause__``)."""


class StallError(AnalysisError):
    """A bounded-progress watchdog fired: a pipeline stage stopped
    advancing without dying.

    Raised instead of wedging forever when a producer/worker is alive
    but makes no progress within the stall timeout
    (``AnalysisConfig.stall_timeout_sec`` / ``RA_STALL_TIMEOUT``) — a
    hung NFS read, a deadlocked worker, or an injected
    ``ingest.queue.stall`` fault all surface as this typed abort."""


class WireCorrupt(AnalysisError):
    """A stored wire-format row failed its integrity invariant.

    The converter only ever stores valid evaluation rows, so a stored
    (non-padding) row with the valid bit clear means the block was
    damaged after conversion; refusing loudly beats silently skipping
    rows of a corrupted production input."""


class ReformBudgetExhausted(AnalysisError):
    """The elastic supervisor used up ``--max-reforms`` re-formations."""


class AnalyzerContradiction(AnalysisError):
    """Live hit evidence contradicts a static "provably dead" verdict.

    A rule the analyzer certified as unreachable (shadowed / redundant /
    conflict) recorded hits under the SAME ruleset — one of the two
    planes is wrong (analyzer bug, corrupted rule tensor, or damaged
    counters), and a deletion report built from either would be
    untrustworthy.  Raised loudly instead of publishing the
    contradiction as if both facts could hold (ISSUE 12: "hit +
    shadow-verdict -> typed error, never silent")."""


class InjectedFault(AnalysisError):
    """A deterministic fault fired by an armed plan (runtime/faults.py).

    Typed as AnalysisError on purpose: chaos schedules assert every
    faulted run ends in a typed abort or a bit-identical report, and an
    injected failure crossing an un-wrapping propagation path must not
    break that invariant by surfacing raw."""


# ---------------------------------------------------------------------------
# CLI exit codes: supervisors and operators branch on the failure class.
# Documented in README "Exit codes"; keep the two tables in sync.
# ---------------------------------------------------------------------------

EXIT_OK = 0
#: generic analysis error (parse failure, missing input, uncategorized)
EXIT_ANALYSIS = 1
#: bad usage / invalid configuration (argparse-level and ValueError)
EXIT_USAGE = 2
#: a checkpoint exists but cannot be trusted (torn write, bit rot, CRC)
EXIT_CHECKPOINT_CORRUPT = 3
#: checkpoint/resume identity mismatch (foreign ruleset/geometry/input)
EXIT_CHECKPOINT_MISMATCH = 4
#: the feed tier failed (dead worker, corrupt wire block, producer bug)
EXIT_FEED = 5
#: a watchdog bounded a hang (stall, formation timeout)
EXIT_STALL = 6
#: elastic re-formation budget exhausted (--max-reforms)
EXIT_REFORM_BUDGET = 7


def exit_code_for(exc: BaseException) -> int:
    """Map a typed runtime error to its documented CLI exit code.

    Ordered most-specific-first; anything unrecognized (including plain
    AnalysisError) keeps the historical catch-all code 1.
    """
    if isinstance(exc, CheckpointCorrupt):
        return EXIT_CHECKPOINT_CORRUPT
    if isinstance(exc, (CheckpointMismatch, ResumeInputMismatch)):
        return EXIT_CHECKPOINT_MISMATCH
    if isinstance(exc, StallError):
        return EXIT_STALL
    if isinstance(exc, ReformBudgetExhausted):
        return EXIT_REFORM_BUDGET
    if isinstance(
        exc, (FeedWorkerError, IngestError, WireCorrupt, NativeParserUnavailable)
    ):
        return EXIT_FEED
    return EXIT_ANALYSIS
