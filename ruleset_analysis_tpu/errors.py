"""Framework error types (jax-free so the CLI can import them cheaply)."""


class AnalysisError(RuntimeError):
    """Base class for user-facing runtime errors."""


class CheckpointMismatch(AnalysisError):
    """Snapshot belongs to a different ruleset or sketch geometry."""


class CheckpointCorrupt(AnalysisError):
    """The pointed-to snapshot exists but cannot be decoded.

    Raised LOUDLY instead of silently starting the analysis from scratch:
    a truncated/bit-flipped snapshot usually means storage trouble, and a
    fresh-start would discard the operator's resume intent without a
    trace.  Recovery: delete the snapshot directory (or fix the storage)
    and rerun."""


class ResumeInputMismatch(AnalysisError):
    """Input stream is shorter than the snapshot's consumed-line offset."""


class NativeParserUnavailable(AnalysisError):
    """The C++ parser was requested but its library cannot be built/loaded."""


class FeedWorkerError(AnalysisError):
    """A parse feed worker (process or thread) died or reported failure.

    Raised by the multi-worker feed tiers instead of hanging on a
    completion that will never arrive — a worker killed by the OS (OOM),
    a crashed parse, or a poisoned descriptor all surface as this typed
    error within the liveness timeout."""
