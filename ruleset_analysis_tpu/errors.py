"""Framework error types (jax-free so the CLI can import them cheaply)."""


class AnalysisError(RuntimeError):
    """Base class for user-facing runtime errors."""


class CheckpointMismatch(AnalysisError):
    """Snapshot belongs to a different ruleset or sketch geometry."""


class CheckpointCorrupt(AnalysisError):
    """The pointed-to snapshot exists but cannot be decoded.

    Raised LOUDLY instead of silently starting the analysis from scratch:
    a truncated/bit-flipped snapshot usually means storage trouble, and a
    fresh-start would discard the operator's resume intent without a
    trace.  Recovery: delete the snapshot directory (or fix the storage)
    and rerun."""


class ResumeInputMismatch(AnalysisError):
    """Input stream is shorter than the snapshot's consumed-line offset."""


class NativeParserUnavailable(AnalysisError):
    """The C++ parser was requested but its library cannot be built/loaded."""


class FeedWorkerError(AnalysisError):
    """A parse feed worker (process or thread) died or reported failure.

    Raised by the multi-worker feed tiers instead of hanging on a
    completion that will never arrive — a worker killed by the OS (OOM),
    a crashed parse, or a poisoned descriptor all surface as this typed
    error within the liveness timeout."""


class IngestError(AnalysisError):
    """The prefetch producer failed with an untyped exception.

    The pipelined ingest engine re-raises producer-side failures at the
    consumer's next pull; failures that are not already AnalysisError
    subclasses are wrapped in this so the chaos invariant — every failed
    run exits with a TYPED error — holds for arbitrary producer bugs
    (the original exception rides ``__cause__``)."""


class StallError(AnalysisError):
    """A bounded-progress watchdog fired: a pipeline stage stopped
    advancing without dying.

    Raised instead of wedging forever when a producer/worker is alive
    but makes no progress within the stall timeout
    (``AnalysisConfig.stall_timeout_sec`` / ``RA_STALL_TIMEOUT``) — a
    hung NFS read, a deadlocked worker, or an injected
    ``ingest.queue.stall`` fault all surface as this typed abort."""


class WireCorrupt(AnalysisError):
    """A stored wire-format row failed its integrity invariant.

    The converter only ever stores valid evaluation rows, so a stored
    (non-padding) row with the valid bit clear means the block was
    damaged after conversion; refusing loudly beats silently skipping
    rows of a corrupted production input."""


class ReformBudgetExhausted(AnalysisError):
    """The elastic supervisor used up ``--max-reforms`` re-formations."""


class AnalyzerContradiction(AnalysisError):
    """Live hit evidence contradicts a static "provably dead" verdict.

    A rule the analyzer certified as unreachable (shadowed / redundant /
    conflict) recorded hits under the SAME ruleset — one of the two
    planes is wrong (analyzer bug, corrupted rule tensor, or damaged
    counters), and a deletion report built from either would be
    untrustworthy.  Raised loudly instead of publishing the
    contradiction as if both facts could hold (ISSUE 12: "hit +
    shadow-verdict -> typed error, never silent")."""


class WalQuarantine(AnalysisError):
    """The serve ingest write-ahead log refused an unusable segment.

    Raised only when the WAL directory itself cannot be opened or
    created; a CRC-corrupt record inside a segment never raises — the
    segment is quarantined (renamed aside), the lost records are counted
    exactly where the seq arithmetic allows, and replay continues with
    the next segment (DESIGN §19)."""


class SupervisorFenced(AnalysisError):
    """A distributed-serve supervisor lost its leadership lease.

    Raised by the merge/publication plane the moment a stale supervisor
    would otherwise publish: either its own lease renewals have been
    failing longer than the lease TTL (it must assume a successor may
    already hold the lease), or it has OBSERVED a higher fencing term on
    disk (a successor definitely won).  Publishing anyway could produce
    two different publications for one window id — the split-brain
    failure mode the fencing term exists to make impossible — so the
    stale supervisor aborts typed (exit 8) instead.  The successor's
    replay of the durable epoch spools re-publishes anything this
    supervisor had pending, bit-identically (runtime/lease.py,
    DESIGN §23)."""


class InjectedFault(AnalysisError):
    """A deterministic fault fired by an armed plan (runtime/faults.py).

    Typed as AnalysisError on purpose: chaos schedules assert every
    faulted run ends in a typed abort or a bit-identical report, and an
    injected failure crossing an un-wrapping propagation path must not
    break that invariant by surfacing raw."""


# ---------------------------------------------------------------------------
# Transient-vs-permanent classification (DESIGN §19).  The retry engine
# (runtime/retrypolicy.py) consults this at every wrapped seam: a
# TRANSIENT failure is worth re-attempting with backoff (the fault is in
# the environment and may clear — a flaky transfer, EINTR, a socket in
# TIME_WAIT, a saturated disk queue); a PERMANENT one never clears by
# waiting (a typed refusal, a missing file, a permission wall, a
# programming error) and must escalate immediately.  One table, one
# function — so the drivers, the listeners, and the checkpoint plane can
# never disagree about what is worth retrying.
# ---------------------------------------------------------------------------

import errno as _errno

#: OSError errnos that describe environmental, possibly-clearing faults.
TRANSIENT_ERRNOS = frozenset(
    getattr(_errno, name)
    for name in (
        "EAGAIN", "EINTR", "EIO", "EBUSY", "ENOBUFS", "ENOMEM",
        "EADDRINUSE", "ECONNRESET", "ECONNREFUSED", "ECONNABORTED",
        "ENETDOWN", "ENETUNREACH", "ENETRESET", "EHOSTUNREACH",
        "ETIMEDOUT", "EPIPE", "ESTALE", "EDQUOT", "ENOSPC",
    )
    if hasattr(_errno, name)
)

#: Substrings of jax/XLA RuntimeError messages that mark environmental
#: device/runtime faults (gRPC status tokens) rather than program bugs.
TRANSIENT_XLA_TOKENS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED")


def is_transient(exc: BaseException) -> bool:
    """True when ``exc`` describes a fault a bounded retry may clear.

    Order matters: InjectedFault (the chaos tier's stand-in for exactly
    these environmental faults) is transient by definition, every OTHER
    typed AnalysisError is a deliberate refusal and therefore permanent,
    and the os-level classes split by errno.  Anything unrecognized is
    permanent — retrying an unknown failure can only mask a bug.
    """
    if isinstance(exc, InjectedFault):
        return True
    if isinstance(exc, AnalysisError):
        return False  # typed refusals (corrupt ckpt, mismatch...) never retry
    if isinstance(exc, (FileNotFoundError, PermissionError, IsADirectoryError,
                        NotADirectoryError)):
        return False
    if isinstance(exc, (ConnectionError, InterruptedError, BlockingIOError,
                        TimeoutError)):
        return True  # includes socket.timeout and ECONNRESET et al.
    if isinstance(exc, OSError):
        return exc.errno in TRANSIENT_ERRNOS
    if isinstance(exc, RuntimeError):
        # XlaRuntimeError subclasses RuntimeError; only the gRPC-status
        # environmental classes qualify (a shape error must escalate)
        msg = str(exc)
        return any(tok in msg for tok in TRANSIENT_XLA_TOKENS)
    return False


# ---------------------------------------------------------------------------
# CLI exit codes: supervisors and operators branch on the failure class.
# Documented in README "Exit codes"; keep the two tables in sync.
# ---------------------------------------------------------------------------

EXIT_OK = 0
#: generic analysis error (parse failure, missing input, uncategorized)
EXIT_ANALYSIS = 1
#: bad usage / invalid configuration (argparse-level and ValueError)
EXIT_USAGE = 2
#: a checkpoint exists but cannot be trusted (torn write, bit rot, CRC)
EXIT_CHECKPOINT_CORRUPT = 3
#: checkpoint/resume identity mismatch (foreign ruleset/geometry/input)
EXIT_CHECKPOINT_MISMATCH = 4
#: the feed tier failed (dead worker, corrupt wire block, producer bug)
EXIT_FEED = 5
#: a watchdog bounded a hang (stall, formation timeout)
EXIT_STALL = 6
#: elastic re-formation budget exhausted (--max-reforms)
EXIT_REFORM_BUDGET = 7
#: a distributed-serve supervisor was fenced by a newer leadership term
EXIT_FENCED = 8

#: Human names for the documented codes — the ``doctor`` tool's first
#: lookup (exit codes 3-8 each map to a runbook entry in its diagnosis;
#: see tools/doctor.py and README "Exit codes").
EXIT_CODE_NAMES = {
    EXIT_OK: "ok",
    EXIT_ANALYSIS: "analysis-error",
    EXIT_USAGE: "usage",
    EXIT_CHECKPOINT_CORRUPT: "checkpoint-corrupt",
    EXIT_CHECKPOINT_MISMATCH: "checkpoint-mismatch",
    EXIT_FEED: "feed-failure",
    EXIT_STALL: "stall",
    EXIT_REFORM_BUDGET: "reform-budget-exhausted",
    EXIT_FENCED: "supervisor-fenced",
}


def exit_code_for(exc: BaseException) -> int:
    """Map a typed runtime error to its documented CLI exit code.

    Ordered most-specific-first; anything unrecognized (including plain
    AnalysisError) keeps the historical catch-all code 1.
    """
    if isinstance(exc, CheckpointCorrupt):
        return EXIT_CHECKPOINT_CORRUPT
    if isinstance(exc, (CheckpointMismatch, ResumeInputMismatch)):
        return EXIT_CHECKPOINT_MISMATCH
    if isinstance(exc, StallError):
        return EXIT_STALL
    if isinstance(exc, ReformBudgetExhausted):
        return EXIT_REFORM_BUDGET
    if isinstance(exc, SupervisorFenced):
        return EXIT_FENCED
    if isinstance(
        exc, (FeedWorkerError, IngestError, WireCorrupt, NativeParserUnavailable)
    ):
        return EXIT_FEED
    return EXIT_ANALYSIS
