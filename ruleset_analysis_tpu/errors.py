"""Framework error types (jax-free so the CLI can import them cheaply)."""


class AnalysisError(RuntimeError):
    """Base class for user-facing runtime errors."""


class CheckpointMismatch(AnalysisError):
    """Snapshot belongs to a different ruleset or sketch geometry."""


class ResumeInputMismatch(AnalysisError):
    """Input stream is shorter than the snapshot's consumed-line offset."""


class NativeParserUnavailable(AnalysisError):
    """The C++ parser was requested but its library cannot be built/loaded."""
