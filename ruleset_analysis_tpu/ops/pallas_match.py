"""Pallas TPU kernel for the first-match scan (alternative to ops.match).

The XLA-fused predicate (ops/match.py) keeps the VPU reasonably busy,
but it re-decides tiling per shape and materializes block temporaries at
the compiler's discretion.  This kernel pins the layout explicitly:

- line fields live along SUBLANES ([BLOCK_LINES, 1] per field), rule
  fields along LANES ([1, 128] per rule tile), so one VPU op evaluates
  128 rules for 8 lines;
- the whole (transposed, lane-padded) rule tensor stays resident in
  VMEM across the batch grid; the running min over rule tiles is a
  register carry in a ``fori_loop`` — nothing [B, R]-shaped ever exists;
- first-match == min matching global rule index, as in ops.match
  (pack.py emits rows in config order — the parity-critical invariant).

Use :func:`first_match_rows_pallas` as a drop-in for
``ops.match.first_match_rows``; ``tests/test_pallas_match.py`` pins
equality (interpret mode on CPU, compiled on TPU) and ``bench_suite.py
pallas`` compares throughput.  The r5 compiled A/B measured this kernel
at 0.15-0.26x the XLA-fused predicate (the [N,1] field layout wastes
VMEM 128x, forcing small grid blocks, while XLA tiles the same
compare-reduce freely) — "xla" stays the default BY MEASUREMENT; select
with ``AnalysisConfig(match_impl="pallas")`` where a different balance
holds.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..hostside.pack import (
    R_ACL,
    R_DHI,
    R_DLO,
    R_DPHI,
    R_DPLO,
    R_PHI,
    R_PLO,
    R_SHI,
    R_SLO,
    R_SPHI,
    R_SPLO,
    RULE_COLS,
)
from .match import NO_MATCH

_U32 = jnp.uint32
_I32 = jnp.int32
#: Python-int twin of ops.match.NO_MATCH — pallas kernels cannot capture
#: module-level jax arrays, only literals.
_NO_MATCH = 0xFFFFFFFF
#: int32 in-kernel sentinel: Mosaic TPU has no lowering for reductions
#: over UNSIGNED integers (first compiled run, r5 TPU window:
#: "NotImplementedError: Reductions over unsigned integers"), so the
#: running-min over rule tiles is carried in int32 — row indices are far
#: below 2^31 — and mapped back to the uint32 NO_MATCH at the kernel
#: boundary, keeping callers bit-compatible with ops.match.
_NO_MATCH_I32 = 0x7FFFFFFF

#: Lines per grid step (sublane-major).  A [BLOCK_LINES, 1] u32 block is
#: physically tiled (8, 128), so it occupies BLOCK_LINES x 128 lanes of
#: VMEM — 512 KB at 1024 lines.  Seven such blocks (6 in + 1 out), double-
#: buffered across the grid, plus the [BLOCK_LINES, RULE_TILE] compare
#: temporary must fit the 16 MB scoped-vmem limit; 4096 OOM'd at 28 MB on
#: the first real-TPU compile (r5 window), 1024 leaves ~2x headroom.
BLOCK_LINES = 1024

#: Rules per lane tile — the VPU lane width.
RULE_TILE = 128


def tile_first_match(fields: tuple, rules, n_tiles: int):
    """Shared kernel body: min matching global row per line, over rule tiles.

    ``fields`` = (acl, proto, src, sport, dst, dport) as [BLOCK_LINES, 1]
    u32 VALUES; ``rules`` is the [RULE_COLS, Rp] field-major ref.  The one
    definition of the tile predicate — ops/pallas_fused.py reuses it, so
    a predicate change (e.g. a new tuple field) lands in every pallas
    kernel at once.  Returns the [BLOCK_LINES, 1] running-min rows.
    """
    a, p, s, sp, d, dp = fields

    def body(t, best):
        sl = pl.ds(t * RULE_TILE, RULE_TILE)

        def row(c):
            return rules[c, sl][None, :]  # [1, RULE_TILE]

        def in_range(lo_c, hi_c, x):
            # unsigned wraparound range check (see ops.match._block_min_row):
            # one subtract + one compare per range instead of two compares
            # + an AND; pack/aclparse + load_packed validation guarantee
            # lo <= hi
            lo = row(lo_c)
            return (x - lo) <= (row(hi_c) - lo)

        ok = (
            (row(R_ACL) == a)
            & in_range(R_PLO, R_PHI, p)
            & in_range(R_SLO, R_SHI, s)
            & in_range(R_SPLO, R_SPHI, sp)
            & in_range(R_DLO, R_DHI, d)
            & in_range(R_DPLO, R_DPHI, dp)
        )
        idx = (
            lax.broadcasted_iota(_I32, (1, RULE_TILE), 1)
            + (t * RULE_TILE).astype(_I32)
        )
        cand = jnp.where(ok, jnp.broadcast_to(idx, ok.shape), _I32(_NO_MATCH_I32))
        return jnp.minimum(best, jnp.min(cand, axis=1, keepdims=True))

    init = jnp.full((a.shape[0], 1), _NO_MATCH_I32, dtype=_I32)
    best = lax.fori_loop(0, n_tiles, body, init)
    return jnp.where(best == _I32(_NO_MATCH_I32), _U32(_NO_MATCH), best.astype(_U32))


def _kernel(acl, proto, src, sport, dst, dport, rules, out, *, n_tiles: int):
    """One batch block vs every rule tile; running-min carry over tiles.

    Refs: six [BLOCK_LINES, 1] u32 line fields; rules [RULE_COLS, R]
    u32 (field-major, lane-padded); out [BLOCK_LINES, 1] u32.
    """
    out[:] = tile_first_match(
        (acl[:], proto[:], src[:], sport[:], dst[:], dport[:]),
        rules, n_tiles,
    )


def prep_rules(rules: jnp.ndarray) -> jnp.ndarray:
    """[R, RULE_COLS] row-major -> [RULE_COLS, Rp] field-major, lane-padded.

    Padding columns carry NO_MATCH in the ACL field so they never match
    (mirrors pack.py's NO_ACL padding rows).
    """
    r = rules.shape[0]
    rp = ((r + RULE_TILE - 1) // RULE_TILE) * RULE_TILE
    t = jnp.transpose(rules.astype(_U32))  # [RULE_COLS, R]
    if rp != r:
        pad = jnp.zeros((RULE_COLS, rp - r), dtype=_U32).at[R_ACL].set(NO_MATCH)
        t = jnp.concatenate([t, pad], axis=1)
    return t


@functools.partial(
    jax.jit, static_argnames=("block_lines", "interpret")
)
def first_match_rows_pallas(
    cols: dict,
    rules_fm: jnp.ndarray,  # [RULE_COLS, Rp] from prep_rules
    block_lines: int = BLOCK_LINES,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Global row index of the first matching ACE per line (pallas path).

    cols: dict of [B] uint32 arrays (acl/proto/src/sport/dst/dport).
    Returns [B] u32, NO_MATCH where no rule matches — bit-compatible
    with ops.match.first_match_rows.  ``interpret=None`` auto-selects:
    compiled on TPU, the pallas interpreter on the CPU test backend
    (pallas_call has no compiled CPU lowering).
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b = cols["acl"].shape[0]
    rp = rules_fm.shape[1]
    assert rp % RULE_TILE == 0
    block_lines = min(block_lines, _ceil_to(b, 8))
    bp = _ceil_to(b, block_lines)

    def field(name):
        v = cols[name]
        if bp != b:  # padded lines produce garbage rows, sliced off below
            v = jnp.concatenate([v, jnp.zeros(bp - b, dtype=_U32)])
        return v.reshape(bp, 1)

    line_spec = pl.BlockSpec((block_lines, 1), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_kernel, n_tiles=rp // RULE_TILE),
        grid=(bp // block_lines,),
        in_specs=[line_spec] * 6
        + [pl.BlockSpec((RULE_COLS, rp), lambda i: (0, 0))],
        out_specs=line_spec,
        out_shape=jax.ShapeDtypeStruct((bp, 1), _U32),
        interpret=interpret,
    )(
        field("acl"),
        field("proto"),
        field("src"),
        field("sport"),
        field("dst"),
        field("dport"),
        rules_fm,
    )
    return out.reshape(bp)[:b]


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def match_keys_pallas(
    cols: dict,
    rules: jnp.ndarray,  # [R, RULE_COLS] row-major (DeviceRuleset.rules)
    rules_fm: jnp.ndarray,  # prep_rules(rules)
    deny_key: jnp.ndarray,
    block_lines: int = BLOCK_LINES,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Count-key per line via the pallas kernel (ops.match.match_keys twin)."""
    from .match import rows_to_keys

    row = first_match_rows_pallas(cols, rules_fm, block_lines, interpret)
    return rows_to_keys(row, rules, deny_key, cols["acl"])
