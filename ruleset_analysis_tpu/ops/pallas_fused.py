"""Fused Pallas kernel: first-match scan + in-VMEM count histograms.

The committed TPU trace (DESIGN.md §8) shows the analysis step is
SCATTER-BOUND: the exact-counts segment-sum (fusion.5, 9.2 ms) is a
batch-sized scatter into a ~260-key register, while the match itself is
only 22% of the step.  This kernel attacks that scatter by never doing
it: while the match block is resident in VMEM it also builds

- ``hist_rows`` ``[1, Rp]`` — how many (valid) lines first-matched each
  rule ROW, and
- ``hist_deny`` ``[1, Ap]`` — how many (valid) lines of each ACL matched
  nothing (implicit deny),

both via lane-tile compare-reduce (``best == iota`` summed over the
sublane axis): O(B * Rp/128) VPU ops instead of a serialized batch-sized
scatter.  The remaining scatter is ROW-sized (Rp ~ 512) not BATCH-sized
(64k): :func:`counts_from_hists` folds the histograms into per-KEY count
deltas with two tiny scatters (rows share keys via R_KEY — multiple ACEs
per rule — and unmatched lines land on their ACL's deny key).

Accumulation across the batch grid uses the standard Pallas revisiting
pattern: the histogram output block maps every grid step to block 0, is
zero-initialized at ``program_id == 0``, and accumulates in VMEM.

Parity: ``tests/test_pallas_fused.py`` pins the counts delta and the
report bit-identical to the XLA path (interpret mode on CPU, compiled on
TPU).  Select with ``AnalysisConfig(match_impl="pallas_fused")`` /
``--match-impl pallas_fused``.  The r5 TPU A/B DECIDED the default:
compiled, this kernel measures 0.19-0.70x the XLA path (and 0.08x
in-step) — "xla" stays the default on measurement; the kernel remains a
selectable alternative and a Mosaic regression probe (DESIGN.md §8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..hostside.pack import R_KEY, RULE_COLS
from .match import rows_to_keys
from .pallas_match import (  # noqa: F401
    BLOCK_LINES,
    RULE_TILE,
    _ceil_to,
    prep_rules,
    tile_first_match,
)

_U32 = jnp.uint32
_NO_MATCH = 0xFFFFFFFF


def _kernel(
    acl, proto, src, sport, dst, dport, valid, rules,
    out_row, hist_rows, hist_deny,
    *, n_tiles: int, n_acl_tiles: int, n_acls: int,
):
    """One batch block: first-match rows + histogram accumulation.

    Refs: seven [BLOCK_LINES, 1] u32 line fields (incl. valid); rules
    [RULE_COLS, Rp] u32 field-major.  out_row [BLOCK_LINES, 1];
    hist_rows [1, Rp] and hist_deny [1, Ap] revisit block 0 every grid
    step and accumulate in VMEM.
    """
    a = acl[:]
    v = valid[:]
    best = tile_first_match(
        (a, proto[:], src[:], sport[:], dst[:], dport[:]), rules, n_tiles
    )
    out_row[:] = best

    # Histogram pass: compare-reduce per lane tile.  Invalid lines are
    # excluded here (the XLA path weights them 0 in segment_counts).
    bv = jnp.where(v > 0, best, _U32(_NO_MATCH - 1))  # valid-masked copy
    # _NO_MATCH-1 can never equal a row index (< Rp << 2^32-2) nor the
    # NO_MATCH sentinel, so invalid lines fall out of BOTH histograms.

    # Clamp out-of-range ACL ids exactly as the keys epilogue does
    # (jnp.minimum(acl, n_acls-1)): a valid line with a corrupt acl gid
    # must land on the LAST ACL's deny key in BOTH the keys and the
    # counts, or delta would diverge from segment_counts(keys, valid).
    # Spelled compare+select: Mosaic has no arith.minui legalization
    # (r5 TPU window), while unsigned compares lower fine.
    a_max = _U32(n_acls - 1)
    a_cl = jnp.where(a > a_max, a_max, a)
    unmatched = jnp.where(bv == _U32(_NO_MATCH), a_cl, _U32(_NO_MATCH - 1))

    @pl.when(pl.program_id(0) == 0)
    def _init():
        hist_rows[:] = jnp.zeros_like(hist_rows[:])
        hist_deny[:] = jnp.zeros_like(hist_deny[:])

    # The tile loops are STATIC Python unrolls accumulating straight into
    # the revisited output refs with static slices: the first compiled
    # run (r5 TPU window) showed Mosaic implements neither unsigned
    # reductions (hence the int32 sums; block counts <= BLOCK_LINES
    # cannot overflow) nor dynamic_update_slice on values (hence no
    # fori_loop-carried accumulator).  Unrolling is n_tiles = Rp/128
    # bodies — trivial at bench/production slab sizes; a 100k-row flat
    # ruleset would pay compile time and should prefer match_impl=xla
    # or plain pallas there.
    def tile_hist(t, masked, ref):
        idx = (
            lax.broadcasted_iota(_U32, (1, RULE_TILE), 1)
            + _U32(t * RULE_TILE)
        )
        eq = (masked == idx).astype(jnp.int32)  # [BLOCK, RULE_TILE]
        part = jnp.sum(eq, axis=0, keepdims=True)  # [1, RULE_TILE]
        sl = slice(t * RULE_TILE, (t + 1) * RULE_TILE)
        ref[:, sl] += part

    for t in range(n_tiles):
        tile_hist(t, bv, hist_rows)
    for t in range(n_acl_tiles):
        tile_hist(t, unmatched, hist_deny)


@functools.partial(
    jax.jit, static_argnames=("n_acls", "block_lines", "interpret")
)
def match_rows_and_hists_pallas(
    cols: dict,
    valid: jnp.ndarray,  # [B] u32
    rules_fm: jnp.ndarray,  # [RULE_COLS, Rp] from prep_rules
    n_acls: int | None = None,
    block_lines: int = BLOCK_LINES,
    interpret: bool | None = None,
):
    """Fused first-match + histograms over the whole batch.

    Returns ``(row [B] u32, hist_rows [Rp] u32, hist_deny [Ap] u32)``
    where ``Ap = ceil(n_acls/128)*128``.  ``interpret=None`` auto-selects
    like :func:`pallas_match.first_match_rows_pallas`.
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b = cols["acl"].shape[0]
    rp = rules_fm.shape[1]
    assert rp % RULE_TILE == 0
    ap = _ceil_to(max(n_acls or 1, 1), RULE_TILE)
    block_lines = min(block_lines, _ceil_to(b, 8))
    bp = _ceil_to(b, block_lines)

    def field(v):
        if bp != b:
            # padding lines carry valid=0 via the valid field below, so
            # they fall out of both histograms; their out rows are sliced
            v = jnp.concatenate([v, jnp.zeros(bp - b, dtype=_U32)])
        return v.reshape(bp, 1)

    line_spec = pl.BlockSpec((block_lines, 1), lambda i: (i, 0))
    hist_rows_spec = pl.BlockSpec((1, rp), lambda i: (0, 0))
    hist_deny_spec = pl.BlockSpec((1, ap), lambda i: (0, 0))
    row, hist_rows, hist_deny = pl.pallas_call(
        functools.partial(
            _kernel, n_tiles=rp // RULE_TILE, n_acl_tiles=ap // RULE_TILE,
            n_acls=max(n_acls or 1, 1),
        ),
        grid=(bp // block_lines,),
        in_specs=[line_spec] * 7
        + [pl.BlockSpec((RULE_COLS, rp), lambda i: (0, 0))],
        out_specs=(line_spec, hist_rows_spec, hist_deny_spec),
        out_shape=(
            jax.ShapeDtypeStruct((bp, 1), _U32),
            # int32 histograms (Mosaic unsigned-reduction constraint);
            # per-chunk totals are bounded by the batch size << 2^31.
            jax.ShapeDtypeStruct((1, rp), jnp.int32),
            jax.ShapeDtypeStruct((1, ap), jnp.int32),
        ),
        interpret=interpret,
    )(
        field(cols["acl"]),
        field(cols["proto"]),
        field(cols["src"]),
        field(cols["sport"]),
        field(cols["dst"]),
        field(cols["dport"]),
        field(valid.astype(_U32)),
        rules_fm,
    )
    return (
        row.reshape(bp)[:b],
        hist_rows.reshape(rp).astype(_U32),
        hist_deny.reshape(ap).astype(_U32),
    )


def counts_from_hists(
    hist_rows: jnp.ndarray,  # [Rp] u32
    hist_deny: jnp.ndarray,  # [Ap] u32
    rules: jnp.ndarray,  # [R, RULE_COLS] row-major
    deny_key: jnp.ndarray,  # [n_acls] u32
    n_keys: int,
) -> jnp.ndarray:
    """Fold row/deny histograms into per-KEY count deltas.

    Two ROW-sized scatters (R ~ 512, n_acls ~ tens) replace the
    batch-sized segment-sum scatter — this is the whole point of the
    fusion.  Bit-identical to ``segment_counts(match_keys(...), valid)``:
    rows -> keys via R_KEY (several ACE rows share one rule key), deny
    counts land on each ACL's deny key.  Padding rows never match (their
    hist entries are 0), so their R_KEY=0 contributions add zero.
    """
    # ra.counts: these two row-sized scatters ARE the fused path's
    # counts stage — scoped so devprof attribution (DESIGN §14) and the
    # static scope-coverage lint (DESIGN §18) see them like every other
    # counts formulation.
    with jax.named_scope("ra.counts"):
        r = rules.shape[0]
        delta = jnp.zeros(n_keys, dtype=_U32)
        delta = delta.at[rules[:, R_KEY].astype(_U32)].add(
            hist_rows[:r], mode="drop"
        )
        a = deny_key.shape[0]
        delta = delta.at[deny_key.astype(_U32)].add(hist_deny[:a], mode="drop")
        return delta


def match_keys_and_counts_pallas(
    cols: dict,
    valid: jnp.ndarray,
    rules: jnp.ndarray,
    rules_fm: jnp.ndarray,
    deny_key: jnp.ndarray,
    n_keys: int,
    block_lines: int = BLOCK_LINES,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Count-key per line + per-key count delta, fused (step integration).

    The keys feed the downstream HLL/talker updates exactly as
    ``match_keys`` would; the counts delta replaces ``segment_counts``.
    """
    row, hist_rows, hist_deny = match_rows_and_hists_pallas(
        cols, valid, rules_fm, deny_key.shape[0], block_lines, interpret
    )
    # ra.match: the shared row->key epilogue (xla's match_keys wraps the
    # same call in the same scope)
    with jax.named_scope("ra.match"):
        keys = rows_to_keys(row, rules, deny_key, cols["acl"])
    delta = counts_from_hists(hist_rows, hist_deny, rules, deny_key, n_keys)
    return keys, delta
