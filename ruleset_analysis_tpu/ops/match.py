"""The first-match kernel: the reference mapper's inner loop, TPU-native.

Reference semantics (SURVEY.md §4.3): for each log line, linearly scan the
named ACL's expanded ACEs *in configuration order*; the first row whose
five range predicates all hold wins; no row -> the ACL's implicit deny.

TPU realisation: the per-line × per-rule double loop becomes one batched
``[B, R]`` boolean predicate (pure uint32 compares on the VPU) reduced with
``min`` over masked row indices — first match == smallest matching row
index, because pack.py emits rows in global configuration order.  No
data-dependent control flow; XLA fuses the compare/reduce into a tiled
loop without materialising [B, R] in HBM.

For large rule tensors the rule axis is processed in fixed-size blocks via
``lax.scan`` (running-min carry), bounding VMEM pressure while keeping one
compiled program for any R.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..hostside.pack import (
    R_ACL,
    R_DHI,
    R_DLO,
    R_DPHI,
    R_DPLO,
    R_PHI,
    R_PLO,
    R_SHI,
    R_SLO,
    R_SPHI,
    R_SPLO,
    R_KEY,
    RULE_BLOCK,  # re-export: the kernel-facing name for the block size
)

_U32 = jnp.uint32


def _block_min_row(cols: dict, rules: jnp.ndarray, base: jnp.ndarray) -> jnp.ndarray:
    """Min matching global row index within one rule block; NO_MATCH if none."""
    r = rules.astype(_U32)
    # [B, 1] vs [1, Rb] broadcasts -> [B, Rb] predicate on the VPU
    def col(i):
        return r[:, i][None, :]

    def in_range(lo_col, hi_col, x):
        # unsigned wraparound range check: with lo <= hi (pack.py
        # guarantees it), x in [lo, hi]  <=>  x - lo <= hi - lo.  One
        # subtract + one compare instead of two compares + an AND — and
        # with the rule tensor compiled in as a constant (parallel/step
        # specialization), hi - lo folds away entirely.
        lo = col(lo_col)
        return (x - lo) <= (col(hi_col) - lo)

    acl = cols["acl"][:, None]
    ok = (
        (col(R_ACL) == acl)
        & in_range(R_PLO, R_PHI, cols["proto"][:, None])
        & in_range(R_SLO, R_SHI, cols["src"][:, None])
        & in_range(R_SPLO, R_SPHI, cols["sport"][:, None])
        & in_range(R_DLO, R_DHI, cols["dst"][:, None])
        & in_range(R_DPLO, R_DPHI, cols["dport"][:, None])
    )
    rb = rules.shape[0]
    idx = base + lax.broadcasted_iota(_U32, (1, rb), 1)
    return jnp.min(jnp.where(ok, idx, NO_MATCH), axis=1)


# numpy scalar, NOT jnp: a module-level jnp scalar would initialize the
# JAX backend at import time (it hangs this process when the TPU tunnel
# is down); np.uint32 participates in jnp expressions identically.
NO_MATCH = np.uint32(0xFFFFFFFF)


@functools.partial(jax.jit, static_argnames=("rule_block",))
def first_match_rows(
    cols: dict,
    rules: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Global row index of the first matching ACE per line; NO_MATCH if none.

    cols: dict of [B] uint32 arrays (acl, proto, src, sport, dst, dport).
    rules: [R, RULE_COLS] uint32, R padded to a multiple of rule_block
    (padding rows carry NO_ACL and never match).
    """
    # ra.match named scope: the kernel's HLO ops (and the scan's while
    # loop) carry the stage label for the device attribution plane
    # (runtime/devprof.py, DESIGN §14); trace-time only, zero run cost
    with jax.named_scope("ra.match"):
        r = rules.shape[0]
        if r <= rule_block:
            return _block_min_row(cols, rules, jnp.uint32(0))
        assert r % rule_block == 0, "pad the rule tensor to a multiple of rule_block"
        blocks = rules.reshape(r // rule_block, rule_block, rules.shape[1])

        def body(best, xs):
            block, base = xs
            m = _block_min_row(cols, block, base)
            return jnp.minimum(best, m), None

        bases = (jnp.arange(r // rule_block, dtype=_U32) * _U32(rule_block))
        init = jnp.full(cols["acl"].shape, NO_MATCH, dtype=_U32)
        best, _ = lax.scan(body, init, (blocks, bases))
        return best


def first_match_rows_stacked(
    cols: dict,
    rules3d: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Grouped first-match: vmap of the kernel over stacked rule slabs.

    cols: dict of [G, Bg] uint32 arrays, lines pre-bucketed by ACL gid
    (pack.group_tuples / pack.GroupBuffer); rules3d: [G, Rmax, RULE_COLS]
    from pack.stack_rules.  Returns [G, Bg] LOCAL slab row indices
    (NO_MATCH where nothing matches).  Each line only scans its own ACL's
    slab — O(Rmax) per line instead of the flat path's O(total rows)
    (BASELINE.json config #4).
    """
    return jax.vmap(
        lambda c, r: first_match_rows(c, r, rule_block), in_axes=(0, 0)
    )(cols, rules3d)


def match_keys_stacked(
    cols: dict,
    rules3d: jnp.ndarray,
    deny_key: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Count-key per line for the grouped layout ([G, Bg] in and out)."""
    row = first_match_rows_stacked(cols, rules3d, rule_block)
    with jax.named_scope("ra.match"):
        matched = row != NO_MATCH
        safe_row = jnp.where(matched, row, _U32(0))
        keys3 = rules3d[:, :, R_KEY].astype(_U32)  # [G, Rmax]
        rule_key = jnp.take_along_axis(keys3, safe_row, axis=1)
        acl = jnp.minimum(cols["acl"], _U32(deny_key.shape[0] - 1))
        deny = deny_key.astype(_U32)[acl]
        return jnp.where(matched, rule_key, deny)


def match_keys(
    cols: dict,
    rules: jnp.ndarray,
    deny_key: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Count-key per line: first-match rule key, or the line's ACL's
    implicit-deny key when nothing matches.

    Invalid lines (valid=0) still produce a (meaningless) key; every
    consumer weights by ``cols["valid"]`` so they contribute nothing.
    """
    row = first_match_rows(cols, rules, rule_block)
    with jax.named_scope("ra.match"):
        return rows_to_keys(row, rules, deny_key, cols["acl"])


def rows_to_keys(
    row: jnp.ndarray,
    rules: jnp.ndarray,
    deny_key: jnp.ndarray,
    acl: jnp.ndarray,
) -> jnp.ndarray:
    """Global first-match row -> count key (shared by every match impl).

    NO_MATCH rows land on the line's ACL's implicit-deny key, with
    out-of-range ACL ids clamped to the last ACL — the single definition
    of that fold, so the xla/pallas/pallas_fused epilogues cannot drift.
    """
    matched = row != NO_MATCH
    safe_row = jnp.where(matched, row, _U32(0))
    rule_key = rules[:, R_KEY].astype(_U32)[safe_row]
    deny = deny_key.astype(_U32)[
        jnp.minimum(acl, _U32(deny_key.shape[0] - 1))
    ]
    return jnp.where(matched, rule_key, deny)
