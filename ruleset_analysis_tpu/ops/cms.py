"""Count-min sketch on device: mergeable approximate per-key counts.

The rebuild's replacement for exact reduce-side counting at scale
(BASELINE.json config #2): a ``[depth, width]`` uint32 register file;
update = scatter-add at one multiply-shift bucket per depth row; query =
min over rows (one-sided overestimate, error <= e*N/width w.p. 1-exp(-depth)).
Merging across chips is elementwise ``+`` — exactly a ``psum`` over ICI,
replacing the Hadoop shuffle (SURVEY.md §3c).

Hash constants are fixed module-wide so independently-built sketches merge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .hashing import MS_CONSTANTS, fmix32, mul_shift

_U32 = jnp.uint32


def cms_init(width: int, depth: int) -> jnp.ndarray:
    if width < 2 or width & (width - 1):
        raise ValueError(f"cms width must be a power of two >= 2, got {width}")
    if not 1 <= depth <= len(MS_CONSTANTS):
        raise ValueError(f"cms depth must be in 1..{len(MS_CONSTANTS)}, got {depth}")
    return jnp.zeros((depth, width), dtype=_U32)


def cms_bucket(keys: jnp.ndarray, width: int, depth: int) -> jnp.ndarray:
    """[depth, B] bucket indices for each key (mixed then multiply-shifted)."""
    bits = int(width).bit_length() - 1
    mixed = fmix32(keys)
    consts = jnp.asarray(MS_CONSTANTS[:depth])  # [d]
    return mul_shift(mixed[None, :], consts[:, None], bits)


def cms_update(cms: jnp.ndarray, keys: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """Scatter-add ``weights`` for ``keys`` into every depth row.

    Traces under the ``ra.cms`` named scope so the batch-sized scatter —
    historically the dominant opaque fusion of the device step — carries
    its stage label in HLO metadata and profiler traces (DESIGN §14).
    A caller wrapping this in its own ``ra.*`` scope (the talker plane's
    ``ra.talk``) wins: classification takes the OUTERMOST scope.
    """
    with jax.named_scope("ra.cms"):
        depth, width = cms.shape
        buckets = cms_bucket(keys, width, depth)  # [d, B]
        rows = jnp.arange(depth, dtype=_U32)[:, None]
        flat_idx = (rows * _U32(width) + buckets).reshape(-1)
        w = jnp.broadcast_to(weights.astype(_U32)[None, :], buckets.shape).reshape(-1)
        return (
            cms.reshape(-1).at[flat_idx].add(w, mode="drop").reshape(depth, width)
        )


def cms_query(cms: jnp.ndarray, keys: jnp.ndarray) -> jnp.ndarray:
    """Point estimate per key: min over depth rows (device or host via numpy)."""
    depth, width = cms.shape
    buckets = cms_bucket(keys, width, depth)  # [d, B]
    vals = jnp.take_along_axis(jnp.asarray(cms), buckets, axis=1)  # [d, B]
    return jnp.min(vals, axis=0)


def cms_query_np(cms: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """Pure-numpy query for host-side reporting (no device round trip)."""
    depth, width = cms.shape
    bits = int(width).bit_length() - 1
    x = keys.astype(np.uint32)
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x85EBCA6B)
    x ^= x >> np.uint32(13)
    x *= np.uint32(0xC2B2AE35)
    x ^= x >> np.uint32(16)
    out = None
    for d in range(depth):
        b = (x * MS_CONSTANTS[d]) >> np.uint32(32 - bits)
        v = cms[d, b]
        out = v if out is None else np.minimum(out, v)
    return out
