"""JAX device ops: the TPU-native replacements for the reference hot loops.

The reference's per-line Python loops (``mapper.py``'s first-match scan,
``reducer.py``'s key-sum — SURVEY.md §4.3/§4.4) become batched, branch-free
array programs here: everything is uint32 arithmetic over packed columns,
with no data-dependent Python control flow, so XLA can tile it onto the TPU
vector unit and fuse the reductions.
"""
