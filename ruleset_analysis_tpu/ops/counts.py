"""Exact per-key hit counts — the reducer's sum, as a device scatter-add.

The reference reducer (SURVEY.md §4.4) sums sorted ``key\\t1`` pairs.  On
device this is one ``segment_sum`` of the valid mask over count keys.  To
stay exact past 2**32 lines without enabling x64 (which would slow every
uint32 op on TPU), totals are carried as a (lo, hi) uint32 pair with manual
carry propagation — per-chunk deltas are < 2**32 by construction, so
``carry = (new_lo < delta)`` detects wrap exactly.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32


def segment_counts(keys: jnp.ndarray, weights: jnp.ndarray, n_keys: int) -> jnp.ndarray:
    """[B] keys + [B] uint32 weights -> [n_keys] uint32 per-key sums."""
    return jnp.zeros(n_keys, dtype=_U32).at[keys].add(
        weights.astype(_U32), mode="drop"
    )


def add64(lo: jnp.ndarray, hi: jnp.ndarray, delta: jnp.ndarray):
    """(lo, hi) uint32 pair += delta (uint32), exact 64-bit accumulation."""
    new_lo = lo + delta
    carry = (new_lo < delta).astype(_U32)
    return new_lo, hi + carry


def to_u64(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Host-side: recombine the pair into numpy uint64."""
    return np.asarray(hi, dtype=np.uint64) * np.uint64(1 << 32) + np.asarray(lo, dtype=np.uint64)
