"""Exact per-key hit counts — the reducer's sum, as a device scatter-add.

The reference reducer (SURVEY.md §4.4) sums sorted ``key\\t1`` pairs.  On
device this is one ``segment_sum`` of the valid mask over count keys.  To
stay exact past 2**32 lines without enabling x64 (which would slow every
uint32 op on TPU), totals are carried as a (lo, hi) uint32 pair with manual
carry propagation — per-chunk deltas are < 2**32 by construction, so
``carry = (new_lo < delta)`` detects wrap exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32

# Every formulation traces under the ``ra.counts`` named scope, so HLO
# ops (and therefore profiler fusions) carry the stage label instead of
# an opaque ``fusion.N`` — the attribution substrate runtime/devprof.py
# classifies device time by (DESIGN §14).  Scopes are trace-time only:
# zero runtime cost, bit-identical outputs.


def segment_counts(keys: jnp.ndarray, weights: jnp.ndarray, n_keys: int) -> jnp.ndarray:
    """[B] keys + [B] uint32 weights -> [n_keys] uint32 per-key sums."""
    with jax.named_scope("ra.counts"):
        return jnp.zeros(n_keys, dtype=_U32).at[keys].add(
            weights.astype(_U32), mode="drop"
        )


def segment_counts_matmul(
    keys: jnp.ndarray, weights: jnp.ndarray, n_keys: int
) -> jnp.ndarray:
    """One-hot matmul formulation of :func:`segment_counts`.

    ``[B] f32 @ [B, n_keys] one-hot -> [n_keys]`` rides the MXU instead
    of issuing a batch-sized scatter — the committed TPU trace shows the
    scatter (fusion.5) at 9.2 ms/step while the MXU sits idle
    (DESIGN.md §8).  Exact because every product is 0/1 and per-key
    per-chunk sums are < 2^24 (f32 integer range): guarded at trace time,
    falling back to the scatter for pathological batch sizes.  Keys out
    of range contribute to no column (the one-hot row is all zero) —
    same semantics as the scatter's ``mode="drop"``.
    """
    if keys.shape[0] >= 1 << 24:
        return segment_counts(keys, weights, n_keys)
    with jax.named_scope("ra.counts"):
        iota = jnp.arange(n_keys, dtype=_U32)
        onehot = (keys[:, None] == iota[None, :]).astype(jnp.float32)
        return jnp.dot(weights.astype(jnp.float32), onehot).astype(_U32)


def segment_counts_reduce(
    keys: jnp.ndarray, weights: jnp.ndarray, n_keys: int
) -> jnp.ndarray:
    """Compare-and-reduce formulation: ``counts[k] = sum_b (keys==k)*w``.

    XLA fuses the compare into the reduction (reductions accept fused
    producers, dots do not), so nothing [B, K]-shaped materializes; all
    VPU, no scatter, no MXU.  ``bench_suite.py stage`` measures all three
    formulations; ``AnalysisConfig.counts_impl`` selects per deployment.
    """
    with jax.named_scope("ra.counts"):
        iota = jnp.arange(n_keys, dtype=_U32)
        eq = keys[None, :] == iota[:, None]
        return jnp.sum(jnp.where(eq, weights.astype(_U32), 0), axis=1)


#: counts_impl name -> formulation (all bit-identical; see the stage bench)
SEGMENT_COUNTS_IMPLS = {
    "scatter": segment_counts,
    "matmul": segment_counts_matmul,
    "reduce": segment_counts_reduce,
}


def add64(lo: jnp.ndarray, hi: jnp.ndarray, delta: jnp.ndarray):
    """(lo, hi) uint32 pair += delta (uint32), exact 64-bit accumulation."""
    with jax.named_scope("ra.counts"):
        new_lo = lo + delta
        carry = (new_lo < delta).astype(_U32)
        return new_lo, hi + carry


def to_u64(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Host-side: recombine the pair into numpy uint64."""
    return np.asarray(hi, dtype=np.uint64) * np.uint64(1 << 32) + np.asarray(lo, dtype=np.uint64)
