"""Streaming top-K talkers per ACL (BASELINE.json config #5).

Space-Saving / Misra-Gries is inherently sequential (each update may evict
the current minimum), so a literal port would serialize the TPU.  The
TPU-native shape is the standard "sketch + candidate heap" decomposition:

- device: a dedicated count-min sketch over (acl, src) pair hashes absorbs
  every line (mergeable, psum-able like any CMS); per chunk, ``lax.top_k``
  over the chunk's own CMS estimates surfaces the strongest candidates —
  all batched, no data-dependent control flow;
- host: a small :class:`TopKTracker` folds each chunk's candidates into a
  bounded per-ACL summary (evict-min, keep-max-estimate), the cheap
  sequential part that touches only ``k`` items per chunk.

Heavy hitters by definition recur across chunks, so candidates they miss in
one chunk they get in the next; the tracker's estimates come from the
global CMS, not per-chunk counts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .cms import cms_query, cms_update
from .hashing import fmix32, hash_pair

_U32 = jnp.uint32


#: Chunk-local candidate table size.  Far larger than any realistic k, so
#: within one chunk a heavy hitter rarely loses its slot to a collision;
#: across chunks the slot hash is re-salted (see ``salt``), so no pair of
#: talkers can collide persistently.
CAND_SLOTS = 1 << 15


def cand_slot(pair: jnp.ndarray, salt: jnp.ndarray | int, slots: int) -> jnp.ndarray:
    """Candidate-table slot of each (acl, src) pair hash.

    ONE definition shared by the scatter path below and the sorted
    segment-reduce path (ops/sorted_update.py): the two formulations must
    place every pair in the SAME slot or their selected candidates — and
    therefore reports — could diverge.
    """
    return fmix32(pair ^ jnp.asarray(salt, dtype=_U32)) & _U32(slots - 1)


def sample_cols(acl, src, valid, salt: jnp.ndarray | int, sample_shift: int):
    """Salt-rotated strided sample of the batch (candidate SELECTION only).

    Extracted from :func:`select_candidates` so the sorted formulation
    samples identically; see there for why the phase rotates with the
    chunk salt.  Degrades to the full batch when a shard is smaller than
    the stride (shapes are static, so this resolves at trace time).
    """
    if sample_shift and acl.shape[0] >= (1 << sample_shift):
        stride = 1 << sample_shift
        bs = (acl.shape[0] // stride) * stride
        phase = jnp.asarray(salt, dtype=_U32) % _U32(stride)

        def col(x):
            return jnp.take(x[:bs].reshape(-1, stride), phase, axis=1)

        return col(acl), col(src), col(valid)
    return acl, src, valid


def cand_k(k: int, b: int, sample_shift: int) -> int:
    """Static candidate count after sampling: min(k, sampled length)."""
    if sample_shift and b >= (1 << sample_shift):
        return min(k, b >> sample_shift)
    return min(k, b)


def select_from_tables(cnt, rep, acl, src, talk_cms, k: int):
    """Top-k selection over an already-built candidate table.

    ``cnt``/``rep`` are the per-slot frequency and representative-line
    tables (however they were built — batch-sized scatters or the sorted
    segment reduce); ``acl``/``src`` are the arrays ``rep``'s line
    indices point into.  Estimates come from the (merged) global talker
    CMS, so the host tracker's values stay chunk-order invariant.
    """
    with jax.named_scope("ra.topk"):
        top_cnt, top_slot = lax.top_k(cnt.astype(jnp.int32), k)
        rep_idx = rep[top_slot]
        safe = jnp.maximum(rep_idx, 0)
        ca, cs = acl[safe], src[safe]
        est = cms_query(talk_cms, hash_pair(ca, cs))
        ok = ((rep_idx >= 0) & (top_cnt > 0)).astype(_U32)
        return ca * ok, cs * ok, est * ok


def maybe_select(fn, salt: jnp.ndarray | int, topk_every: int, k: int):
    """Run candidate-producing ``fn`` on selection chunks only.

    ``topk_every > 1`` defers top-K candidate selection to every Nth
    chunk (Space-Saving spirit: heavy hitters recur, so a stride sample
    of CHUNKS still surfaces them while the talker CMS keeps absorbing
    every line).  Deterministic in the chunk salt — resume replays the
    same selection schedule — and skipped chunks yield est=0 candidates,
    which the host tracker ignores.  ``topk_every == 1`` is a straight
    call: the pre-existing single-knob HLO is untouched.
    """
    if topk_every <= 1:
        return fn(None)
    with jax.named_scope("ra.topk"):
        z = jnp.zeros(k, dtype=_U32)
        sel = jnp.asarray(salt, dtype=_U32) % _U32(topk_every) == _U32(0)
        return lax.cond(sel, fn, lambda _: (z, z, z), None)


def talker_chunk_update(
    talk_cms: jnp.ndarray,
    acl: jnp.ndarray,
    src: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
    salt: jnp.ndarray | int = 0,
    sample_shift: int = 0,
    topk_every: int = 1,
):
    """Absorb one chunk; return (new_cms, cand_acl, cand_src, cand_est).

    The candidate estimates are post-update global CMS estimates, masked to
    0 for suppressed/empty slots so they can never displace real candidates.
    ``salt`` re-randomizes the candidate table's slot assignment; stream
    drivers pass the chunk counter so collisions cannot persist across
    chunks while staying deterministic for checkpoint resume.

    ``sample_shift > 0`` selects candidates from every 2**shift-th line
    only.  The CMS update — and therefore every reported estimate — still
    covers the full batch; the sample only shrinks the candidate-table
    scatters (the scatter-bound share of the TPU step).  Deterministic:
    the stride is fixed, so resume replays identically.

    ``topk_every > 1`` additionally defers selection to every Nth chunk
    (see :func:`maybe_select`); the CMS still absorbs every chunk.
    """
    with jax.named_scope("ra.talk"):
        pair = hash_pair(acl, src)
        new_cms = cms_update(talk_cms, pair, valid)
    k1 = min(k, acl.shape[0])

    def _select(_):
        return select_candidates(
            new_cms, acl, src, valid, k1, salt=salt, sample_shift=sample_shift
        )

    cand = maybe_select(
        _select, salt, topk_every, cand_k(k1, acl.shape[0], sample_shift)
    )
    return (new_cms, *cand)


def select_candidates(talk_cms, acl, src, valid, k, slots: int = CAND_SLOTS,
                      salt: jnp.ndarray | int = 0, sample_shift: int = 0):
    """Top-k distinct (acl, src) candidates of this chunk.

    ``sample_shift > 0`` selects from 1/2**shift of the lines: the batch
    reshapes to [b', stride] rows and ONE column — rotated by ``salt`` so
    the phase differs every chunk — feeds the candidate table.  The
    rotation matters for grouped (stacked) layouts, where lines are
    group-major and a FIXED stride phase could alias entire ACL groups
    out of the sample forever; with rotation every line position is
    sampled within ``stride`` chunks, restoring the heavy-hitters-recur
    argument.  Estimates are untouched (they come from ``talk_cms``,
    which absorbed every line).

    A naive "dedup then top_k over the batch" costs a full argsort of the
    batch (the old implementation dominated the whole analysis step).
    Instead, pairs hash into a ``slots``-sized chunk-local table with two
    scatters — per-slot frequency (add) and a representative line index
    (max) — and ``top_k`` runs over the small table, not the batch:

      batch-sized work: 2 scatters + 1 hash  (vs argsort + scatter + top_k)
      table-sized work: one top_k over ``slots``

    Selection ranks by in-chunk frequency (Misra-Gries flavored); the
    reported estimate is the global post-update CMS estimate of each
    winner, so the host tracker's values stay chunk-order invariant.
    Distinct pairs colliding in a slot: the pair whose LAST occurrence in
    the chunk is later holds the representative (the max-line-index
    scatter), the other is suppressed — and the slot's rank is inflated
    by both pairs' counts.  The same two pairs collide in every chunk
    with the same ``salt``, which is why streaming callers pass a
    per-chunk salt: the suppressed pair surfaces under the next salt.
    """
    # A per-shard batch smaller than the stride would leave bs == 0 and
    # feed ZERO candidates every chunk — an empty talker report with no
    # warning (ADVICE r4).  Degrade to exact full-batch selection instead;
    # shapes are static so this resolves at trace time.
    with jax.named_scope("ra.topk"):
        acl, src, valid = sample_cols(acl, src, valid, salt, sample_shift)
        k = min(k, acl.shape[0])
        b = acl.shape[0]
        pair = hash_pair(acl, src)
        slot = cand_slot(pair, salt, slots)
        v32 = valid.astype(_U32)
        cnt = jnp.zeros(slots, dtype=_U32).at[slot].add(v32, mode="drop")
        iota = lax.broadcasted_iota(jnp.int32, (b,), 0)
        rep = (
            jnp.full(slots, -1, dtype=jnp.int32)
            .at[slot]
            .max(jnp.where(v32 > 0, iota, -1), mode="drop")
        )
    return select_from_tables(cnt, rep, acl, src, talk_cms, k)


class TopKTracker:
    """Host-side bounded per-ACL talker summary fed by chunk candidates."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._tables: dict[int, dict[int, int]] = {}

    def offer(self, acl: int, src: int, est: int) -> None:
        if est <= 0:
            return
        t = self._tables.setdefault(acl, {})
        if src in t:
            t[src] = max(t[src], est)
            return
        if len(t) < self.capacity:
            t[src] = est
            return
        victim = min(t, key=t.get)
        if est > t[victim]:
            del t[victim]
            t[src] = est

    def offer_chunk(self, cand_acl, cand_src, cand_est) -> None:
        for a, s, e in zip(cand_acl.tolist(), cand_src.tolist(), cand_est.tolist()):
            self.offer(int(a), int(s), int(e))

    def top(self, acl: int, k: int) -> list[tuple[int, int]]:
        t = self._tables.get(acl, {})
        # canonical tie order (estimate desc, then source asc): candidate
        # ARRIVAL order varies with the mesh world size (per-device top-k
        # slices), and reports must render identically across scale
        # events for the autoscale bit-identity law
        return sorted(t.items(), key=lambda kv: (-kv[1], kv[0]))[:k]

    def acls(self) -> list[int]:
        return list(self._tables)

    def tables(self) -> dict[int, dict[int, int]]:
        """Snapshot-serializable view of the per-ACL summaries."""
        return {acl: dict(t) for acl, t in self._tables.items()}
