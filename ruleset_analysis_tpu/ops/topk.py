"""Streaming top-K talkers per ACL (BASELINE.json config #5).

Space-Saving / Misra-Gries is inherently sequential (each update may evict
the current minimum), so a literal port would serialize the TPU.  The
TPU-native shape is the standard "sketch + candidate heap" decomposition:

- device: a dedicated count-min sketch over (acl, src) pair hashes absorbs
  every line (mergeable, psum-able like any CMS); per chunk, ``lax.top_k``
  over the chunk's own CMS estimates surfaces the strongest candidates —
  all batched, no data-dependent control flow;
- host: a small :class:`TopKTracker` folds each chunk's candidates into a
  bounded per-ACL summary (evict-min, keep-max-estimate), the cheap
  sequential part that touches only ``k`` items per chunk.

Heavy hitters by definition recur across chunks, so candidates they miss in
one chunk they get in the next; the tracker's estimates come from the
global CMS, not per-chunk counts.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .cms import cms_query, cms_update
from .hashing import hash_pair

_U32 = jnp.uint32


def talker_chunk_update(
    talk_cms: jnp.ndarray,
    acl: jnp.ndarray,
    src: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
):
    """Absorb one chunk; return (new_cms, cand_acl, cand_src, cand_est).

    The candidate estimates are post-update global CMS estimates, masked to
    0 for invalid lines so they can never displace real candidates.
    """
    pair = hash_pair(acl, src)
    new_cms = cms_update(talk_cms, pair, valid)
    cand = select_candidates(new_cms, acl, src, valid, min(k, acl.shape[0]))
    return (new_cms, *cand)


def select_candidates(talk_cms, acl, src, valid, k):
    """Top-k distinct (acl, src) candidates of this batch by CMS estimate.

    Dedup within the chunk first: a hot talker fills thousands of lines,
    and top_k over raw per-line scores would return k copies of it,
    crowding out ranks 2..k.  Keep only each pair's first occurrence
    (sort once, mark sorted-adjacent duplicates, scatter the mask back).
    """
    pair = hash_pair(acl, src)
    est = cms_query(talk_cms, pair) * valid.astype(_U32)
    order = jnp.argsort(pair)
    sorted_pair = pair[order]
    first_sorted = jnp.concatenate(
        [jnp.ones(1, dtype=jnp.bool_), sorted_pair[1:] != sorted_pair[:-1]]
    )
    first = jnp.zeros_like(first_sorted).at[order].set(first_sorted)
    score = jnp.minimum(est * first.astype(_U32), _U32(0x7FFFFFFF)).astype(jnp.int32)
    _, idx = lax.top_k(score, k)
    return acl[idx], src[idx], est[idx] * first[idx].astype(_U32)


class TopKTracker:
    """Host-side bounded per-ACL talker summary fed by chunk candidates."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._tables: dict[int, dict[int, int]] = {}

    def offer(self, acl: int, src: int, est: int) -> None:
        if est <= 0:
            return
        t = self._tables.setdefault(acl, {})
        if src in t:
            t[src] = max(t[src], est)
            return
        if len(t) < self.capacity:
            t[src] = est
            return
        victim = min(t, key=t.get)
        if est > t[victim]:
            del t[victim]
            t[src] = est

    def offer_chunk(self, cand_acl, cand_src, cand_est) -> None:
        for a, s, e in zip(cand_acl.tolist(), cand_src.tolist(), cand_est.tolist()):
            self.offer(int(a), int(s), int(e))

    def top(self, acl: int, k: int) -> list[tuple[int, int]]:
        t = self._tables.get(acl, {})
        return sorted(t.items(), key=lambda kv: -kv[1])[:k]

    def acls(self) -> list[int]:
        return list(self._tables)

    def tables(self) -> dict[int, dict[int, int]]:
        """Snapshot-serializable view of the per-ACL summaries."""
        return {acl: dict(t) for acl, t in self._tables.items()}
