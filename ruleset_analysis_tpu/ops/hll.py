"""Per-key HyperLogLog registers on device: unique sources per rule.

BASELINE.json config #3: per-rule unique-source cardinality.  Exact per-rule
source *sets* (the oracle's ``sources``) don't fit device memory at scale;
HLL gives ~1.04/sqrt(m) relative error in m uint32 registers per key.

Register file: ``[n_keys, m]`` uint32 (m = 2**p).  Update is one
scatter-max per line: register index from p hash bits, rank = leading-zero
count of an independent hash + 1.  Merge across chips is elementwise
``max`` — a ``pmax`` over ICI; rank 0 (invalid lines) is the identity, so
masking needs no branches.

Estimation runs host-side in numpy at report time (standard HLL estimator
with linear-counting small-range correction).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .hashing import clz32, fmix32

_U32 = jnp.uint32

_HLL_SEED_IDX = 0xB5297A4D
_HLL_SEED_RANK = 0x68E31DA4


def hll_init(n_keys: int, p: int) -> jnp.ndarray:
    return jnp.zeros((n_keys, 1 << p), dtype=_U32)


def hll_reg_rank(
    values: jnp.ndarray, valid: jnp.ndarray, p: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-line (register index, masked rank) — the HLL update's math.

    ONE definition shared by the scatter formulation below and the sorted
    segment-reduce formulation (ops/sorted_update.py), so the two can
    never drift: rank 0 (invalid lines) is the identity for max.
    """
    h_idx = fmix32(values, seed=_HLL_SEED_IDX)
    h_rank = fmix32(values, seed=_HLL_SEED_RANK)
    reg = h_idx >> _U32(32 - p)  # high p bits -> register index
    rank = clz32(h_rank) + _U32(1)  # 1..33
    rank = rank * (valid > 0).astype(_U32)  # invalid -> 0 == identity for max
    return reg, rank


def hll_update(
    hll: jnp.ndarray, keys: jnp.ndarray, values: jnp.ndarray, valid: jnp.ndarray
) -> jnp.ndarray:
    """Fold ``values`` (e.g. src IPs) into each line's key's registers.

    ``valid`` is a uint32 *weight* plane: 0 masks the line out, any
    nonzero value counts it — the gate is boolean (``valid > 0``), never
    multiplicative, because HLL is idempotent in repetitions of the same
    (key, value): a coalesced row carrying weight w must update exactly
    as w identical raw lines would (DESIGN §11).
    """
    with jax.named_scope("ra.hll"):
        p = int(hll.shape[1]).bit_length() - 1
        reg, rank = hll_reg_rank(values, valid, p)
        return hll.at[keys, reg].max(rank, mode="drop")


# ---------------------------------------------------------------------------
# Host-side estimation (numpy), SURVEY.md §5 sketch-accuracy contract.
# ---------------------------------------------------------------------------


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


#: The value space folded into the registers: uint32 IPv4 sources.
VALUE_SPACE = 2.0**32


def hll_estimate_np(registers: np.ndarray) -> np.ndarray:
    """[K, m] registers -> [K] cardinality estimates (float64, host).

    Large-range behavior (VERDICT r3 weak #5): the classic 32-bit HLL
    correction ``-2^32 ln(1 - E/2^32)`` compensates for hash COLLISIONS —
    distinct inputs landing on the same 32-bit hash, which makes the raw
    estimate count distinct hashes instead of distinct inputs.  This
    design has no such collisions: :func:`..ops.hashing.fmix32` is a
    bijection on uint32 (murmur3 finalizer — invertible), so n distinct
    IPv4 sources are n distinct rank-hash values, and the rank hash is
    full-width (independent of the p index bits) rather than the classic
    truncated 32-p bits.  Applying the classic correction here would
    INFLATE estimates ~39% at n = 2^31 (it assumes E under-counts).  The
    property tests in test_sketches.py verify the uncorrected estimator
    holds the 1.04/sqrt(m) bound at 2^31 and beyond by exact inverse-CDF
    simulation of the without-replacement register distribution.

    The one true large-range artifact is rank truncation as n approaches
    the full 2^32 value space (every register saturates toward rank 33,
    and the raw estimate overshoots toward ``alpha * 2^33``); since the
    folded values ARE uint32 IPv4 addresses, the estimate is capped at
    the size of that space, which is also the exact answer in the
    saturated regime.
    """
    reg = np.asarray(registers, dtype=np.float64)
    k, m = reg.shape
    raw = _alpha(m) * m * m / np.sum(np.exp2(-reg), axis=1)
    zeros = np.sum(reg == 0, axis=1)
    # linear counting when the raw estimate is small and registers remain empty
    small = (raw <= 2.5 * m) & (zeros > 0)
    with np.errstate(divide="ignore"):
        linear = m * np.log(m / np.maximum(zeros, 1e-12))
    return np.minimum(np.where(small, linear, raw), VALUE_SPACE)
