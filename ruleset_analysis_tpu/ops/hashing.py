"""uint32 hashing primitives for the device-side sketches.

All sketch ops (CMS bucket choice, HLL register/rank, talker pair codes)
need cheap, well-mixed uint32 hashes that vectorize on the TPU VPU.  We use
the murmur3 finalizer (fmix32) seeded per use, and multiply-shift for
power-of-two bucket ranges — both are a handful of integer ops per lane,
wrap-around arithmetic being exactly what uint32 gives us under XLA.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

_U32 = jnp.uint32

#: Odd multipliers for multiply-shift hashing, one per CMS depth row.
#: Fixed (not seeded) so sketches from different runs/devices merge.
MS_CONSTANTS = np.array(
    [0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F, 0x165667B1, 0x9E3779B9, 0x85EBCA6B, 0xC2B2AE35],
    dtype=np.uint32,
)

from ..config import MAX_CMS_DEPTH as _MAX_CMS_DEPTH  # noqa: E402

assert len(MS_CONSTANTS) >= _MAX_CMS_DEPTH, "config.MAX_CMS_DEPTH exceeds hash constants"


def fmix32(x: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """murmur3 finalizer: a full-avalanche uint32 -> uint32 mix."""
    x = x.astype(_U32) ^ _U32(seed)
    x = x ^ (x >> 16)
    x = x * _U32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * _U32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_pair(a: jnp.ndarray, b: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """Mix two uint32 streams into one (order-sensitive)."""
    h = fmix32(a, seed=seed)
    return fmix32(h ^ b.astype(_U32) * _U32(0x9E3779B1), seed=seed + 0x51ED)


def mul_shift(x: jnp.ndarray, const: int | jnp.ndarray, bits: int) -> jnp.ndarray:
    """Multiply-shift hash onto ``[0, 2**bits)`` — bucket index for sketches."""
    return (x.astype(_U32) * _U32(const)) >> _U32(32 - bits)


def clz32(x: jnp.ndarray) -> jnp.ndarray:
    """Count leading zeros of uint32, branch-free (5-step binary search).

    Exact integer computation — no float log tricks, which round near
    powers of two and would bias HLL ranks.
    """
    x = x.astype(_U32)
    n = jnp.full(x.shape, 32, dtype=_U32)
    for shift in (16, 8, 4, 2, 1):
        big = x >= (_U32(1) << _U32(shift))
        n = jnp.where(big, n - _U32(shift), n)
        x = jnp.where(big, x >> _U32(shift), x)
    # here x is 0 or 1; subtract the final bit
    return n - x
