"""IPv6 first-match kernel: 4x uint32 limb addresses, same semantics.

The v6 twin of ops/match.py (DESIGN.md "IPv6 position"; SURVEY.md §8.0
tags v6 "later as 4x uint32" — this is that extension).  Rows live in a
SEPARATE [R6, RULE6_COLS] tensor (pack.py) so the v4 hot path is
untouched; splitting by family preserves first-match order because a
packet can only match ACEs of its own family.

The per-field predicate changes only for addresses: the single uint32
wraparound range check becomes a 128-bit lexicographic bound pair over
four big-endian limbs — 7 compares + 3 and/or folds per bound, all VPU
elementwise, still branch-free and fusable.  Scalar fields (proto,
ports) keep the wraparound check.  Everything else (block scan over the
rule axis, min matching row == first match, NO_MATCH -> implicit deny
key) mirrors the v4 kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..hostside.pack import (
    R6_ACL,
    R6_DHI,
    R6_DLO,
    R6_DPHI,
    R6_DPLO,
    R6_KEY,
    R6_PHI,
    R6_PLO,
    R6_SHI,
    R6_SLO,
    R6_SPHI,
    R6_SPLO,
    RULE_BLOCK,
)
from .match import NO_MATCH

_U32 = jnp.uint32


def _ge128(x, lo):
    """x >= lo lexicographically; x/lo are 4-tuples of [B,1]/[1,Rb] u32."""
    x0, x1, x2, x3 = x
    l0, l1, l2, l3 = lo
    return (x0 > l0) | (
        (x0 == l0)
        & ((x1 > l1) | ((x1 == l1) & ((x2 > l2) | ((x2 == l2) & (x3 >= l3)))))
    )


def _le128(x, hi):
    x0, x1, x2, x3 = x
    h0, h1, h2, h3 = hi
    return (x0 < h0) | (
        (x0 == h0)
        & ((x1 < h1) | ((x1 == h1) & ((x2 < h2) | ((x2 == h2) & (x3 <= h3)))))
    )


def _block_min_row6(cols: dict, rules: jnp.ndarray, base: jnp.ndarray) -> jnp.ndarray:
    """Min matching global v6 row index within one rule block."""
    r = rules.astype(_U32)

    def col(i):
        return r[:, i][None, :]

    def limbs_rule(c0):
        return tuple(col(c0 + i) for i in range(4))

    def limbs_line(name):
        return tuple(cols[f"{name}{i}"][:, None] for i in range(4))

    def in_range(lo_col, hi_col, x):
        # scalar wraparound check, as in ops.match (lo <= hi guaranteed)
        lo = col(lo_col)
        return (x - lo) <= (col(hi_col) - lo)

    src = limbs_line("src")
    dst = limbs_line("dst")
    ok = (
        (col(R6_ACL) == cols["acl"][:, None])
        & in_range(R6_PLO, R6_PHI, cols["proto"][:, None])
        & _ge128(src, limbs_rule(R6_SLO))
        & _le128(src, limbs_rule(R6_SHI))
        & in_range(R6_SPLO, R6_SPHI, cols["sport"][:, None])
        & _ge128(dst, limbs_rule(R6_DLO))
        & _le128(dst, limbs_rule(R6_DHI))
        & in_range(R6_DPLO, R6_DPHI, cols["dport"][:, None])
    )
    rb = rules.shape[0]
    idx = base + lax.broadcasted_iota(_U32, (1, rb), 1)
    return jnp.min(jnp.where(ok, idx, NO_MATCH), axis=1)


@functools.partial(jax.jit, static_argnames=("rule_block",))
def first_match_rows6(
    cols: dict,
    rules6: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Global row index of the first matching v6 ACE per line.

    cols: dict of [B] uint32 arrays — acl, proto, sport, dport plus the
    address limbs src0..src3 / dst0..dst3 (big-endian).  rules6:
    [R6, RULE6_COLS] uint32, padded to a rule_block multiple when it
    exceeds one block (padding rows carry NO_ACL).  Returns [B] u32,
    NO_MATCH where nothing matches.
    """
    # ra.match6 named scope: stage label for the attribution plane
    # (runtime/devprof.py, DESIGN §14) — v6 time never hides under v4's
    with jax.named_scope("ra.match6"):
        r = rules6.shape[0]
        if r <= rule_block:
            return _block_min_row6(cols, rules6, jnp.uint32(0))
        assert r % rule_block == 0, "pad the v6 rule tensor to a rule_block multiple"
        blocks = rules6.reshape(r // rule_block, rule_block, rules6.shape[1])

        def body(best, xs):
            block, base = xs
            return jnp.minimum(best, _block_min_row6(cols, block, base)), None

        bases = jnp.arange(r // rule_block, dtype=_U32) * _U32(rule_block)
        init = jnp.full(cols["acl"].shape, NO_MATCH, dtype=_U32)
        best, _ = lax.scan(body, init, (blocks, bases))
        return best


def match_keys6(
    cols: dict,
    rules6: jnp.ndarray,
    deny_key: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Count-key per v6 line: first-match rule key or the ACL's deny key."""
    row = first_match_rows6(cols, rules6, rule_block)
    with jax.named_scope("ra.match6"):
        matched = row != NO_MATCH
        safe_row = jnp.where(matched, row, _U32(0))
        rule_key = rules6[:, R6_KEY].astype(_U32)[safe_row]
        deny = deny_key.astype(_U32)[
            jnp.minimum(cols["acl"], _U32(deny_key.shape[0] - 1))
        ]
        return jnp.where(matched, rule_key, deny)


def first_match_rows6_stacked(
    cols: dict,
    rules3d: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Grouped v6 first-match: vmap over stacked per-ACL limb slabs.

    cols: dict of [G, Bg] uint32 arrays (v6 field names incl. limbs),
    lines pre-bucketed by ACL gid; rules3d: [G, R6max, RULE6_COLS] from
    pack.stack_rules6.  Returns [G, Bg] LOCAL slab rows (NO_MATCH where
    nothing matches) — O(R6max) per line instead of O(total v6 rows),
    the BASELINE config-#4 scaling for the v6 family.
    """
    return jax.vmap(
        lambda c, r: first_match_rows6(c, r, rule_block), in_axes=(0, 0)
    )(cols, rules3d)


def match_keys6_stacked(
    cols: dict,
    rules3d: jnp.ndarray,
    deny_key: jnp.ndarray,
    rule_block: int = RULE_BLOCK,
) -> jnp.ndarray:
    """Count-key per v6 line for the grouped layout ([G, Bg] in and out)."""
    row = first_match_rows6_stacked(cols, rules3d, rule_block)
    with jax.named_scope("ra.match6"):
        return _keys_from_rows6_stacked(cols, rules3d, deny_key, row)


def _keys_from_rows6_stacked(cols, rules3d, deny_key, row):
    matched = row != NO_MATCH
    safe_row = jnp.where(matched, row, _U32(0))
    keys3 = rules3d[:, :, R6_KEY].astype(_U32)  # [G, R6max]
    rule_key = jnp.take_along_axis(keys3, safe_row, axis=1)
    acl = jnp.minimum(cols["acl"], _U32(deny_key.shape[0] - 1))
    deny = deny_key.astype(_U32)[acl]
    return jnp.where(matched, rule_key, deny)


def fold_src32(cols: dict) -> jnp.ndarray:
    """[B] u32 sketch identity for a v6 source address.

    HLL / talker registers key sources by one uint32 lane; v6 sources
    fold their four limbs through multiply-xor mixing.  Distinct
    addresses collide with probability ~2^-32 per pair — negligible
    against the sketches' own error floors.  The fold is deterministic
    and documented so reports can label these ids as v6 digests.
    """
    with jax.named_scope("ra.match6"):
        h = cols["src0"] * _U32(0x9E3779B1)
        h = (h ^ cols["src1"]) * _U32(0x85EBCA77)
        h = (h ^ cols["src2"]) * _U32(0xC2B2AE3D)
        h = (h ^ cols["src3"]) * _U32(0x27D4EB2F)
        return h ^ (h >> _U32(15))
