"""Pairwise rule-relation kernel: the static half of first-match semantics.

The match kernel (ops/match.py) asks "which rule does this PACKET hit";
this kernel asks the packet-free dual: "how do two RULE rows relate as
boxes in the 5-field interval space".  Under first-match-wins (SURVEY
§5: overlapping rules + implicit deny), an earlier row that *covers* a
later one makes the later one unreachable, and partial overlaps are the
raw material of union-shadowing — so the per-pair relations below are
the entire input of the static analyzer (runtime/staticanalysis.py).

TPU realisation: a pair tile ``[Ti, Tj]`` of boolean predicates from
pure uint32 compares on the VPU — the same broadcast-compare shape as
the match kernel's ``[B, R]`` predicate, with rules on BOTH axes.  The
O(R²) pair space is walked in fixed-size tiles so one compiled program
serves any R (and the tile grid shards embarrassingly over devices —
each tile touches only its two row blocks).  Everything runs under
``jax.named_scope("ra.overlap")`` so tile time shows up as its own
stage in the device attribution plane (runtime/devprof.py, DESIGN §14).

Relation semantics per ordered pair (a = row of the i-block, b = row of
the j-block), all conditioned on both rows being real (not NO_ACL
padding) and in the SAME ACL — cross-ACL rows never interact under
first-match:

  ``covered[a, b]``  row b's box contains row a's box on ALL 5 fields
                     (proto, src, sport, dst, dport) — b fully masks a
                     if b comes earlier in config order.
  ``overlap[a, b]``  the boxes intersect on ALL 5 fields — b can steal
                     at least one of a's packets if earlier.

``covered`` implies ``overlap`` (a box is non-empty: lo <= hi is a pack
invariant enforced by validate_rule_ranges).  Subset/superset/disjoint/
partial per-pair classes derive from the two matrices:

  disjoint  = ~overlap
  subset    = covered           (a  ⊆ b)
  superset  = covered^T         (a  ⊇ b, read at [b, a])
  partial   = overlap & ~subset & ~superset
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..hostside.pack import _RANGE_COLS, NO_ACL, R_ACL, RULE_COLS

_U32 = jnp.uint32

#: Default pair-tile edge.  512x512 = 256k boolean lanes per predicate —
#: the same VMEM scale as the match kernel's [B, RULE_BLOCK] tiles.
PAIR_TILE = 512

#: (lo, hi) column pairs of the 5 interval fields — derived from the
#: pack layer's canonical range-column table so a rule-tensor layout
#: change cannot silently desynchronize the relation predicates.
_FIELDS = tuple((lo, hi) for lo, hi, _name in _RANGE_COLS)


@jax.jit
def relation_tile(rows_i: jnp.ndarray, rows_j: jnp.ndarray):
    """One pair tile: ``([Ti, RULE_COLS], [Tj, RULE_COLS]) -> (covered,
    overlap)`` boolean ``[Ti, Tj]`` matrices (semantics in the module
    docstring).  Padding rows (acl == NO_ACL) relate to nothing.
    """
    with jax.named_scope("ra.overlap"):
        ri = rows_i.astype(_U32)
        rj = rows_j.astype(_U32)
        acl_i = ri[:, R_ACL][:, None]  # [Ti, 1]
        acl_j = rj[:, R_ACL][None, :]  # [1, Tj]
        same = (acl_i == acl_j) & (acl_i != NO_ACL) & (acl_j != NO_ACL)
        covered = same
        overlap = same
        for lo, hi in _FIELDS:
            li, ha = ri[:, lo][:, None], ri[:, hi][:, None]
            lj, hb = rj[:, lo][None, :], rj[:, hi][None, :]
            covered &= (lj <= li) & (ha <= hb)
            overlap &= jnp.maximum(li, lj) <= jnp.minimum(ha, hb)
        return covered, overlap


def _pad_rows(rows: np.ndarray, to: int) -> np.ndarray:
    """Pad a row block to ``to`` rows with never-matching NO_ACL rows."""
    if rows.shape[0] == to:
        return rows
    out = np.zeros((to, RULE_COLS), dtype=np.uint32)
    out[:, R_ACL] = NO_ACL
    out[: rows.shape[0]] = rows
    return out


def iter_pair_tiles(r: int, tile: int = PAIR_TILE):
    """Tile-grid index iterator: yields ``(i0, i1, j0, j1)`` row ranges.

    Separated from :func:`pair_relations` so drivers that need a seam
    per tile (fault injection, device round-robin, progress) can own
    the loop while reusing the exact same grid.
    """
    for i0 in range(0, r, tile):
        i1 = min(i0 + tile, r)
        for j0 in range(0, r, tile):
            yield i0, i1, j0, min(j0 + tile, r)


def pair_relations(
    rules: np.ndarray,
    tile: int = PAIR_TILE,
    devices: list | None = None,
    on_tile=None,
    lower_only: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Full ``[R, R]`` covered/overlap matrices via fixed-size tiles.

    Every tile is padded to ``[tile, tile]`` so ONE jit compile serves
    the whole grid (and any later ruleset).  ``devices`` round-robins
    tile rows across jax devices — the O(R²) grid is embarrassingly
    shardable because a tile reads only its two row blocks.  ``on_tile``
    (if given) is called once per tile BEFORE it is computed — the
    analyzer threads its ``analyze.tile`` fault site through it.

    ``lower_only`` skips tiles strictly above the diagonal (``j0 > i0``
    — every pair there has ``b > a``), leaving those entries False: the
    analyzer only consumes earlier-row relations, and row order is
    key-ascending, so the upper triangle is provably masked out anyway
    — skipping it drops ~half the O(R²) device work.
    """
    r = rules.shape[0]
    rules = np.ascontiguousarray(rules, dtype=np.uint32)
    covered = np.zeros((r, r), dtype=bool)
    overlap = np.zeros((r, r), dtype=bool)
    if r == 0:
        return covered, overlap
    blocks: dict[tuple[int, int], jnp.ndarray] = {}

    def block(b0: int, b1: int, dev):
        key = (b0, id(dev))
        if key not in blocks:
            padded = _pad_rows(rules[b0:b1], tile)
            blocks[key] = (
                jax.device_put(padded, dev) if dev is not None else jnp.asarray(padded)
            )
        return blocks[key]

    for i0, i1, j0, j1 in iter_pair_tiles(r, tile):
        if lower_only and j0 > i0:
            continue
        if on_tile is not None:
            on_tile(i0, j0)
        dev = devices[(i0 // tile) % len(devices)] if devices else None
        cov, ovl = relation_tile(block(i0, i1, dev), block(j0, j1, dev))
        covered[i0:i1, j0:j1] = np.asarray(cov)[: i1 - i0, : j1 - j0]
        overlap[i0:i1, j0:j1] = np.asarray(ovl)[: i1 - i0, : j1 - j0]
    return covered, overlap


def pair_relations_np(rules: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Pure-numpy twin of :func:`pair_relations` (tests pin agreement)."""
    acl = rules[:, R_ACL]
    same = (acl[:, None] == acl[None, :]) & (acl != NO_ACL)[:, None] & (
        acl != NO_ACL
    )[None, :]
    covered = same.copy()
    overlap = same.copy()
    for lo, hi in _FIELDS:
        li, ha = rules[:, lo][:, None], rules[:, hi][:, None]
        lj, hb = rules[:, lo][None, :], rules[:, hi][None, :]
        covered &= (lj <= li) & (ha <= hb)
        overlap &= np.maximum(li, lj) <= np.minimum(ha, hb)
    return covered, overlap
