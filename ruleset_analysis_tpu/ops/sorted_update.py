"""Sorted segment-reduce register updates (``update_impl='sorted'``).

DESIGN §8's named-stage capture shows the device step SCATTER-BOUND:
the five batch-sized scatters (exact counts, talker CMS, per-key HLL,
candidate count, candidate representative) are ~77% of the TPU step.
This module is the structural alternative — the sort/segment-reduce
half of the MapReduce combiner (Dean & Ghemawat, OSDI '04), applied on
device: sort the batch's register keys once with ``lax.sort``, then
update every register file with segment reductions over the sorted
runs (``indices_are_sorted=True`` scatters — XLA can lower a sorted,
run-grouped scatter without the hazard handling a random-order
batch-sized scatter needs).

Two sort domains per step (DESIGN §15):

- **rule-key domain** — ONE sort of the packed ``key * m + hll_reg``
  composite feeds BOTH the exact-counts segment-sum (major key = the
  count key) and the HLL segment-max (full composite = the flat HLL
  register index).  The composite fits uint32 whenever the HLL register
  file itself fits memory (``n_keys * m`` entries); pathological
  geometries fall back to the scatter forms, value-identically.
- **shared talker index space** — the ``[depth * width]`` talker-CMS
  cells and the ``[slots]`` candidate-table cells concatenate into ONE
  index space ``[depth*width | slots]``, so ONE sort + one segment-sum
  + one segment-max update the talker CMS AND the candidate table
  together ("one gather/sort feeds both").  The slot hash and the CMS
  bucket hash are byte-for-byte the scatter path's (ops/topk.py
  ``cand_slot``, ops/hashing multiply-shift), which is what makes the
  two formulations bit-identical end to end.

Every update is weight-linear (sums of the uint32 weight plane) or
idempotent (HLL max), so the sorted path accepts coalesced/weighted
batches everywhere by construction.  uint32 addition and max are
associative and commutative, so reordering the updates along the sorted
permutation produces bit-identical registers — the property the
scatter-vs-sorted identity matrix in tests/test_sorted_update.py pins.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import counts as count_ops
from . import hll as hll_ops
from .cms import cms_bucket
from .hashing import hash_pair
from .topk import cand_slot, sample_cols

_U32 = jnp.uint32

#: The packed (rule key, HLL register) composite must fit uint32.  Under
#: the default register budget the HLL file itself caps n_keys * m at
#: 2^30 entries, so the guard only fires for raised-budget geometries.
COMPOSITE_LIMIT = 1 << 32


def composite_fits(n_keys: int, m: int) -> bool:
    """True when ``key * m + reg`` sort keys cannot wrap uint32."""
    return n_keys * m < COMPOSITE_LIMIT


def counts_hll_sorted(
    hll: jnp.ndarray,
    keys: jnp.ndarray,
    valid: jnp.ndarray,
    src: jnp.ndarray,
    n_keys: int,
    *,
    need_counts: bool,
):
    """Rule-key domain: one sort feeds exact counts AND the HLL update.

    Returns ``(counts_delta | None, new_hll)``; ``counts_delta`` is the
    [n_keys] per-key weight sum when ``need_counts`` (i.e. the counts
    stage runs the default scatter formulation — matmul/reduce impls
    compose separately and skip it).  ``hll`` may be the live register
    file (single-device in-place semantics) or zeros (the parallel
    delta-then-pmax path); both are just "max into this base".
    """
    m = int(hll.shape[1])
    p = m.bit_length() - 1
    if not composite_fits(n_keys, m):
        delta = (
            count_ops.segment_counts(keys, valid, n_keys) if need_counts else None
        )
        return delta, hll_ops.hll_update(hll, keys, src, valid)
    with jax.named_scope("ra.hll"):
        reg, rank = hll_ops.hll_reg_rank(src, valid, p)
    with jax.named_scope("ra.sort"):
        # out-of-range keys must DROP exactly as the scatters' mode="drop"
        # does: route them to the all-ones sentinel (whose major key
        # 0xFFFFFFFF >> p is >= n_keys by the composite_fits guard) and
        # zero their operands for belt and braces
        oob = keys >= _U32(n_keys)
        ck = jnp.where(oob, _U32(0xFFFFFFFF), keys * _U32(m) + reg)
        w = jnp.where(oob, _U32(0), valid.astype(_U32))
        rk = jnp.where(oob, _U32(0), rank)
        ck_s, w_s, rk_s = lax.sort((ck, w, rk), num_keys=1)
    counts_delta = None
    if need_counts:
        with jax.named_scope("ra.counts"):
            counts_delta = jnp.zeros(n_keys, dtype=_U32).at[ck_s >> _U32(p)].add(
                w_s, mode="drop", indices_are_sorted=True
            )
    with jax.named_scope("ra.hll"):
        new_hll = (
            hll.reshape(-1)
            .at[ck_s]
            .max(rk_s, mode="drop", indices_are_sorted=True)
            .reshape(hll.shape)
        )
    return counts_delta, new_hll


def talker_tables_sorted(
    acl: jnp.ndarray,
    src: jnp.ndarray,
    valid: jnp.ndarray,
    salt: jnp.ndarray,
    *,
    width: int,
    depth: int,
    slots: int,
    sample_shift: int = 0,
    with_candidates: bool = True,
):
    """Shared talker index space: one sort updates CMS + candidate table.

    Returns ``(cms_delta [depth, width], cnt [slots], rep [slots])``.
    ``cms_delta`` sums the full batch's weights per CMS cell (add it to
    the live register file, or psum it first on the parallel path); the
    candidate tables cover the salt-rotated SAMPLE when ``sample_shift``
    is set, exactly like the scatter path.  ``with_candidates=False``
    (a deferred-selection chunk, --topk-every) sorts the CMS cells only
    and returns empty tables — per-cell sums are permutation-invariant,
    so the CMS values are identical either way.
    """
    b = acl.shape[0]
    with jax.named_scope("ra.talk"):
        pair = hash_pair(acl, src)
        # the scatter path's own bucket hash (ops/cms.py) — shared like
        # cand_slot/hll_reg_rank so the formulations can never drift
        buckets = cms_bucket(pair, width, depth)  # [d, B]
        rows = jnp.arange(depth, dtype=_U32)[:, None]
        cms_idx = (rows * _U32(width) + buckets).reshape(-1)  # [d*B]
        w_cms = jnp.broadcast_to(
            valid.astype(_U32)[None, :], (depth, b)
        ).reshape(-1)
    base = depth * width
    if not with_candidates:
        with jax.named_scope("ra.sort"):
            k_s, w_s = lax.sort((cms_idx, w_cms), num_keys=1)
        with jax.named_scope("ra.talk"):
            cms_delta = (
                jnp.zeros(base, dtype=_U32)
                .at[k_s]
                .add(w_s, mode="drop", indices_are_sorted=True)
                .reshape(depth, width)
            )
        return (
            cms_delta,
            jnp.zeros(slots, dtype=_U32),
            jnp.full(slots, -1, dtype=jnp.int32),
        )
    with jax.named_scope("ra.topk"):
        s_acl, s_src, s_valid = sample_cols(acl, src, valid, salt, sample_shift)
        s_pair = pair if s_acl is acl else hash_pair(s_acl, s_src)
        slot = cand_slot(s_pair, salt, slots)
        sv32 = s_valid.astype(_U32)
        iota = lax.broadcasted_iota(jnp.int32, (s_acl.shape[0],), 0)
        vmax_slot = jnp.where(sv32 > 0, iota, -1)
    with jax.named_scope("ra.sort"):
        keys_all = jnp.concatenate([cms_idx, _U32(base) + slot])
        w_all = jnp.concatenate([w_cms, sv32])
        vmax_all = jnp.concatenate(
            [jnp.full(cms_idx.shape[0], -1, dtype=jnp.int32), vmax_slot]
        )
        k_s, w_s, v_s = lax.sort((keys_all, w_all, vmax_all), num_keys=1)
    total = base + slots
    with jax.named_scope("ra.talk"):
        seg_sum = jnp.zeros(total, dtype=_U32).at[k_s].add(
            w_s, mode="drop", indices_are_sorted=True
        )
        cms_delta = seg_sum[:base].reshape(depth, width)
    with jax.named_scope("ra.topk"):
        cnt = seg_sum[base:]
        rep = (
            jnp.full(total, -1, dtype=jnp.int32)
            .at[k_s]
            .max(v_s, mode="drop", indices_are_sorted=True)[base:]
        )
    return cms_delta, cnt, rep
