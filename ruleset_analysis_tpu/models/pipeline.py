"""The analysis pipeline — the "flagship model" of this framework.

One jitted step fuses everything the reference's mapper+reducer pair did
per line (SURVEY.md §4.3/§4.4), over a whole batch:

  batch -> first-match keys -> { exact 64-bit counts, CMS, per-rule HLL,
                                 top-K talker candidates }

The state is a pytree of uint32 register files, every component of which
is mergeable (add for counts/CMS, max for HLL) — the property that makes
multi-chip scale-out a pair of XLA collectives (psum/pmax) instead of a
Hadoop shuffle, and makes checkpoint/resume idempotent.

Batches arrive column-major ``[TUPLE_COLS, B]`` so each field is a
contiguous lane-aligned vector on device.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import AnalysisConfig
from ..hostside.pack import (
    PackedRuleset,
    T_ACL, T_DPORT, T_DST, T_PROTO, T_SPORT, T_SRC, T_VALID,
    T6_ACL, T6_DPORT, T6_DST, T6_PROTO, T6_SPORT, T6_SRC, T6_VALID,
    TUPLE_COLS, TUPLE6_COLS, W_DST, W_META, W_PORTS, W_SRC, W_WEIGHT,
    WIRE_COLS, WIRE_MAX_ACLS, WIREW_COLS,
)
from ..ops import cms as cms_ops
from ..ops import counts as count_ops
from ..ops import hll as hll_ops
from ..ops import topk as topk_ops
from ..ops.match import RULE_BLOCK, match_keys, match_keys_stacked

_U32 = jnp.uint32


class DeviceRuleset(NamedTuple):
    """Device-resident rule tensor (the reference's shipped ACL pickle)."""

    rules: jax.Array  # [R, RULE_COLS] uint32, R % rule_block == 0
    deny_key: jax.Array  # [n_acls] uint32
    #: field-major lane-padded twin for the pallas kernel; None on the
    #: default XLA path (ship_ruleset(match_impl="pallas") fills it)
    rules_fm: jax.Array | None = None


class DeviceRuleset6(NamedTuple):
    """Device-resident IPv6 rule tensor (pack.rules6, limb layout).

    Shares the v4 key universe and deny_key; shipped only when the packed
    ruleset carries v6 rows, so pure-v4 runs never touch the v6 path.
    """

    rules6: jax.Array  # [R6, RULE6_COLS] uint32, R6 % rule_block == 0
    deny_key: jax.Array  # [n_acls] uint32


#: High bit tagged onto ACL gids of IPv6 talker candidates: v6 source
#: identities are 32-bit limb digests (ops.match6.fold_src32), and the tag
#: keeps them from ever merging with a numerically-equal v4 address in the
#: talker tracker.  gids are bounded by WIRE_MAX_ACLS (23 bits), so bit 31
#: is always free; reports strip the tag and render these as v6 digests.
V6_ACL_TAG = np.uint32(0x80000000)


class AnalysisState(NamedTuple):
    """All mergeable device registers for one analysis run."""

    counts_lo: jax.Array  # [K] u32   exact hit counts, low word
    counts_hi: jax.Array  # [K] u32   exact hit counts, high word
    cms: jax.Array  # [d, w] u32      approximate per-key counts
    hll: jax.Array  # [K, m] u32      per-key unique-source registers
    talk_cms: jax.Array  # [d, w] u32 (acl, src) pair counts for top-K


class ChunkOut(NamedTuple):
    """Per-chunk host-bound outputs (top-K candidates)."""

    cand_acl: jax.Array  # [k] u32
    cand_src: jax.Array  # [k] u32
    cand_est: jax.Array  # [k] u32


def batch_cols(batch: jax.Array) -> tuple[dict, jax.Array]:
    """Field columns + valid/weight plane from a batch in ANY layout.

    Accepts the working layout ``[TUPLE_COLS, B]`` (one uint32 lane per
    field), the wire layout ``[WIRE_COLS, B]`` (bit-packed, 16 B/line —
    what the stream driver ships over PCIe; see pack.compact_batch), or
    the WEIGHTED wire layout ``[WIREW_COLS, B]`` (a coalesced batch: the
    extra row carries each unique row's repetition count, which becomes
    the valid plane — every register update is weight-linear in it or
    idempotent, see DESIGN §11).  The layout is static shape information,
    so under jit this is a free Python branch; the wire unpack is three
    shifts and three ands on the VPU — noise next to the match itself.

    Traces under the ``ra.unpack`` named scope (incl. the coalesce
    weight plane): the unpack's HLO ops carry their stage label for the
    device attribution plane (runtime/devprof.py, DESIGN §14).
    """
    u32 = jnp.uint32
    with jax.named_scope("ra.unpack"):
        if batch.shape[-2] in (WIRE_COLS, WIREW_COLS):
            meta = batch[..., W_META, :]
            ports = batch[..., W_PORTS, :]
            cols = {
                "acl": meta & u32(WIRE_MAX_ACLS - 1),
                "proto": meta >> u32(24),
                "src": batch[..., W_SRC, :],
                "sport": ports >> u32(16),
                "dst": batch[..., W_DST, :],
                "dport": ports & u32(0xFFFF),
            }
            if batch.shape[-2] == WIREW_COLS:
                return cols, batch[..., W_WEIGHT, :]
            return cols, (meta >> u32(23)) & u32(1)
        if batch.shape[-2] == TUPLE_COLS:
            cols = {
                "acl": batch[..., T_ACL, :],
                "proto": batch[..., T_PROTO, :],
                "src": batch[..., T_SRC, :],
                "sport": batch[..., T_SPORT, :],
                "dst": batch[..., T_DST, :],
                "dport": batch[..., T_DPORT, :],
            }
            return cols, batch[..., T_VALID, :]
    raise ValueError(
        f"batch field axis must be TUPLE_COLS={TUPLE_COLS} or "
        f"WIRE_COLS={WIRE_COLS}, got shape {batch.shape}"
    )


def batch_cols6(batch: jax.Array) -> tuple[dict, jax.Array]:
    """Field columns + valid mask from a v6 batch in EITHER layout.

    Accepts the working ``[TUPLE6_COLS, B]`` layout or the wire-v2
    ``[WIRE6_COLS, B]`` layout (40 B/line; ports/meta bit-packed exactly
    like the v4 wire words, so the on-device unpack is the same three VPU
    shifts).  Address limbs surface as src0..src3 / dst0..dst3.
    """
    from ..hostside.pack import (
        W6_DST, W6_META, W6_PORTS, W6_SRC, W6_WEIGHT, WIRE6_COLS,
        WIRE6W_COLS,
    )

    u32 = jnp.uint32
    with jax.named_scope("ra.unpack"):
        if batch.shape[-2] in (WIRE6_COLS, WIRE6W_COLS):
            meta = batch[..., W6_META, :]
            ports = batch[..., W6_PORTS, :]
            cols = {
                "acl": meta & u32(WIRE_MAX_ACLS - 1),
                "proto": meta >> u32(24),
                "sport": ports >> u32(16),
                "dport": ports & u32(0xFFFF),
            }
            for i in range(4):
                cols[f"src{i}"] = batch[..., W6_SRC + i, :]
                cols[f"dst{i}"] = batch[..., W6_DST + i, :]
            if batch.shape[-2] == WIRE6W_COLS:
                return cols, batch[..., W6_WEIGHT, :]
            return cols, (meta >> u32(23)) & u32(1)
        if batch.shape[-2] != TUPLE6_COLS:
            raise ValueError(
                f"v6 batch field axis must be TUPLE6_COLS={TUPLE6_COLS} or "
                f"WIRE6_COLS={WIRE6_COLS}, got shape {batch.shape}"
            )
        cols = {
            "acl": batch[..., T6_ACL, :],
            "proto": batch[..., T6_PROTO, :],
            "sport": batch[..., T6_SPORT, :],
            "dport": batch[..., T6_DPORT, :],
        }
        for i in range(4):
            cols[f"src{i}"] = batch[..., T6_SRC + i, :]
            cols[f"dst{i}"] = batch[..., T6_DST + i, :]
        return cols, batch[..., T6_VALID, :]


def pad_rules6(rules6: np.ndarray, rule_block: int = RULE_BLOCK) -> np.ndarray:
    """Pad the v6 rule matrix to a block multiple (NO_ACL padding rows)."""
    from ..hostside.pack import NO_ACL, R6_ACL, RULE6_COLS

    r = rules6.shape[0]
    target = max(rule_block, ((r + rule_block - 1) // rule_block) * rule_block)
    if r == target:
        return rules6
    out = np.zeros((target, RULE6_COLS), dtype=np.uint32)
    out[:, R6_ACL] = NO_ACL
    out[:r] = rules6
    return out


def ship_ruleset6(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> DeviceRuleset6:
    return DeviceRuleset6(
        rules6=jnp.asarray(pad_rules6(packed.rules6, rule_block)),
        deny_key=jnp.asarray(packed.deny_key.astype(np.uint32)),
    )


def ship_ruleset6_host(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> DeviceRuleset6:
    """Numpy twin of :func:`ship_ruleset6` — no backend touched."""
    return DeviceRuleset6(
        rules6=pad_rules6(packed.rules6, rule_block),
        deny_key=packed.deny_key.astype(np.uint32),
    )


def pad_rules(rules: np.ndarray, rule_block: int = RULE_BLOCK) -> np.ndarray:
    """Pad the host rule matrix to a multiple of the scan block size."""
    from ..hostside.pack import NO_ACL, R_ACL, RULE_COLS

    r = rules.shape[0]
    target = max(rule_block, ((r + rule_block - 1) // rule_block) * rule_block)
    if r == target:
        return rules
    out = np.zeros((target, RULE_COLS), dtype=np.uint32)
    out[:, R_ACL] = NO_ACL
    out[:r] = rules
    return out


def ship_ruleset(
    packed: PackedRuleset,
    rule_block: int = RULE_BLOCK,
    match_impl: str = "xla",
) -> DeviceRuleset:
    rules = jnp.asarray(pad_rules(packed.rules, rule_block))
    rules_fm = None
    # pallas_fused is an explicit experimental surface (VERDICT r5 Weak
    # #4: 0.083x vs XLA); the loud warning lives in the step builder
    # (parallel/step.py), which every driver path crosses exactly once
    if match_impl in ("pallas", "pallas_fused"):
        from ..ops import pallas_match

        rules_fm = pallas_match.prep_rules(rules)
    return DeviceRuleset(
        rules=rules,
        deny_key=jnp.asarray(packed.deny_key.astype(np.uint32)),
        rules_fm=rules_fm,
    )


def register_bytes(n_keys: int, cfg: AnalysisConfig) -> dict[str, int]:
    """Per-register-file device memory for this geometry, in bytes."""
    s = cfg.sketch
    return {
        "counts": 2 * 4 * n_keys,
        "cms": 4 * s.cms_depth * s.cms_width,
        "hll": 4 * n_keys * s.hll_m,
        "talk_cms": 4 * s.talk_cms_depth * s.cms_width,
    }


def check_register_budget(n_keys: int, cfg: AnalysisConfig) -> None:
    """Refuse geometries whose registers exceed the configured budget.

    The per-key HLL file (``n_keys * 2**hll_p * 4`` bytes) scales with the
    ruleset: 1M expanded rule keys at the default hll_p=8 is already 1 GiB
    of HBM.  Failing here with a concrete suggestion beats an opaque
    device OOM mid-run.
    """
    sizes = register_bytes(n_keys, cfg)
    total = sum(sizes.values())
    budget = cfg.register_memory_budget_bytes
    if total <= budget:
        return
    non_hll = total - sizes["hll"]
    fit_p = -1
    for p in range(cfg.sketch.hll_p, 0, -1):
        if non_hll + 4 * n_keys * (1 << p) <= budget:
            fit_p = p
            break
    hint = (
        f"try --hll-p {fit_p}"
        if fit_p > 0
        else "even hll_p=1 does not fit; raise register_memory_budget_bytes "
        "or shrink the ruleset/cms geometry"
    )
    raise ValueError(
        f"sketch registers need {total / 2**20:.0f} MiB "
        f"(hll {sizes['hll'] / 2**20:.0f} MiB = {n_keys} keys x "
        f"{cfg.sketch.hll_m} registers x 4 B) but the budget is "
        f"{budget / 2**20:.0f} MiB; {hint}"
    )


def init_state(n_keys: int, cfg: AnalysisConfig) -> AnalysisState:
    check_register_budget(n_keys, cfg)
    s = cfg.sketch
    return AnalysisState(
        counts_lo=jnp.zeros(n_keys, dtype=_U32),
        counts_hi=jnp.zeros(n_keys, dtype=_U32),
        cms=cms_ops.cms_init(s.cms_width, s.cms_depth),
        hll=hll_ops.hll_init(n_keys, s.hll_p),
        talk_cms=cms_ops.cms_init(s.cms_width, s.talk_cms_depth),
    )


def init_state_host(n_keys: int, cfg: AnalysisConfig) -> AnalysisState:
    """Numpy twin of :func:`init_state` — same pytree, no JAX backend touched.

    Lets entry points build example arguments without initializing any
    device plugin (jax.jit accepts numpy leaves); the driver's own jit call
    is then the first and only backend contact.
    """
    check_register_budget(n_keys, cfg)
    s = cfg.sketch
    u32 = np.uint32
    return AnalysisState(
        counts_lo=np.zeros(n_keys, dtype=u32),
        counts_hi=np.zeros(n_keys, dtype=u32),
        cms=np.zeros((s.cms_depth, s.cms_width), dtype=u32),
        hll=np.zeros((n_keys, s.hll_m), dtype=u32),
        talk_cms=np.zeros((s.talk_cms_depth, s.cms_width), dtype=u32),
    )


def ship_ruleset_host(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> DeviceRuleset:
    """Numpy twin of :func:`ship_ruleset` (XLA match path only) — no backend."""
    return DeviceRuleset(
        rules=pad_rules(packed.rules, rule_block),
        deny_key=packed.deny_key.astype(np.uint32),
        rules_fm=None,
    )


def _update_registers(
    state: AnalysisState,
    keys: jax.Array,  # [B] u32 count keys (matched rule / implicit deny)
    valid: jax.Array,  # [B] u32 weight plane (0 = invalid, w = w raw lines)
    src: jax.Array,  # [B] u32 source IPs
    acl: jax.Array,  # [B] u32 ACL gids
    *,
    n_keys: int,
    topk_k: int,
    exact_counts: bool,
    salt: jax.Array | int = 0,
    topk_sample_shift: int = 0,
    counts_delta: jax.Array | None = None,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    """Shared register tail: the reducer's whole job, for any match layout."""
    # One bincount into the (small) key space feeds BOTH the exact counts
    # and the CMS: count-min updates are linear in per-key increments, so
    # updating from [n_keys] aggregated deltas instead of [B] raw lines is
    # bit-identical and turns the batch-sized CMS scatter into a
    # key-space-sized one (~free; the batch-sized scatter dominated the
    # whole step at 1M-line chunks).  counts_delta: the fused pallas
    # kernel already built the bincount in VMEM (mirrors parallel/step.py
    # _merge_tail — keep the two tails in lockstep).
    #
    # update_impl="sorted" (DESIGN §15): the batch-sized scatters become
    # segment reductions over sorted key runs (ops/sorted_update.py) —
    # bit-identical by add/max associativity.  counts_impl composes: the
    # matmul/reduce counts formulations are already scatter-free, so the
    # sorted path only takes over the counts stage at the default
    # "scatter" setting.
    if update_impl == "sorted":
        from ..ops import sorted_update as sorted_ops

        need = counts_delta is None and counts_impl == "scatter"
        sorted_delta, hll = sorted_ops.counts_hll_sorted(
            state.hll, keys, valid, src, n_keys, need_counts=need
        )
        if counts_delta is None:
            counts_delta = (
                sorted_delta
                if need
                else count_ops.SEGMENT_COUNTS_IMPLS[counts_impl](
                    keys, valid, n_keys
                )
            )
    else:
        if counts_delta is None:
            counts_delta = count_ops.SEGMENT_COUNTS_IMPLS[counts_impl](
                keys, valid, n_keys
            )
        hll = hll_ops.hll_update(state.hll, keys, src, valid)
    delta = counts_delta
    if exact_counts:
        lo, hi = count_ops.add64(state.counts_lo, state.counts_hi, delta)
    else:
        lo, hi = state.counts_lo, state.counts_hi
    cms = cms_ops.cms_update(state.cms, jnp.arange(n_keys, dtype=_U32), delta)
    if update_impl == "sorted":
        from ..ops import sorted_update as sorted_ops

        salt_u = jnp.asarray(salt, dtype=_U32)
        dt, wt = state.talk_cms.shape

        def _tables(sel):
            return sorted_ops.talker_tables_sorted(
                acl, src, valid, salt_u, width=wt, depth=dt,
                slots=topk_ops.CAND_SLOTS, sample_shift=topk_sample_shift,
                with_candidates=sel,
            )

        if topk_every > 1:
            cms_delta, cnt, rep = jax.lax.cond(
                salt_u % _U32(topk_every) == _U32(0),
                lambda _: _tables(True),
                lambda _: _tables(False),
                None,
            )
        else:
            cms_delta, cnt, rep = _tables(True)
        talk_cms = state.talk_cms + cms_delta
        s_acl, s_src, _sv = topk_ops.sample_cols(
            acl, src, valid, salt_u, topk_sample_shift
        )
        ca, cs, ce = topk_ops.select_from_tables(
            cnt, rep, s_acl, s_src, talk_cms,
            min(topk_k, s_acl.shape[0]),
        )
    else:
        talk_cms, ca, cs, ce = topk_ops.talker_chunk_update(
            state.talk_cms, acl, src, valid, topk_k, salt=salt,
            sample_shift=topk_sample_shift, topk_every=topk_every,
        )
    return (
        AnalysisState(counts_lo=lo, counts_hi=hi, cms=cms, hll=hll, talk_cms=talk_cms),
        ChunkOut(cand_acl=ca, cand_src=cs, cand_est=ce),
    )


def analysis_step(
    state: AnalysisState,
    ruleset: DeviceRuleset,
    batch: jax.Array,  # [TUPLE_COLS, B] uint32, column-major
    *,
    n_keys: int,
    topk_k: int,
    exact_counts: bool = True,
    rule_block: int = RULE_BLOCK,
    salt: jax.Array | int = 0,
    match_impl: str = "xla",
    topk_sample_shift: int = 0,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    """One fused device step over a batch of packed log lines.

    ``batch`` may be the working ``[TUPLE_COLS, B]`` layout or the wire
    ``[WIRE_COLS, B]`` layout (see :func:`batch_cols`).
    """
    cols, valid = batch_cols(batch)
    counts_delta = None
    if match_impl == "pallas_fused" and ruleset.rules_fm is not None:
        from ..ops import pallas_fused

        keys, counts_delta = pallas_fused.match_keys_and_counts_pallas(
            cols, valid, ruleset.rules, ruleset.rules_fm, ruleset.deny_key,
            n_keys,
        )
    elif match_impl == "pallas" and ruleset.rules_fm is not None:
        from ..ops import pallas_match

        keys = pallas_match.match_keys_pallas(
            cols, ruleset.rules, ruleset.rules_fm, ruleset.deny_key
        )
    else:
        keys = match_keys(cols, ruleset.rules, ruleset.deny_key, rule_block)
    return _update_registers(
        state, keys, valid, cols["src"], cols["acl"],
        n_keys=n_keys, topk_k=topk_k, exact_counts=exact_counts, salt=salt,
        topk_sample_shift=topk_sample_shift, counts_delta=counts_delta,
        counts_impl=counts_impl, update_impl=update_impl,
        topk_every=topk_every,
    )


def analysis_step6(
    state: AnalysisState,
    ruleset6: DeviceRuleset6,
    batch6: jax.Array,  # [TUPLE6_COLS, B6] uint32, column-major
    *,
    n_keys: int,
    topk_k: int,
    exact_counts: bool = True,
    rule_block: int = RULE_BLOCK,
    salt: jax.Array | int = 0,
    topk_sample_shift: int = 0,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    """One fused device step over a batch of v6 lines.

    Updates the SAME register state as the v4 step (shared key universe):
    exact counts and CMS key by rule key; HLL / talker source identity is
    the 32-bit limb digest (ops.match6.fold_src32), with the talker ACL
    gid tagged V6_ACL_TAG so v6 digests never merge with v4 addresses.
    """
    from ..ops.match6 import fold_src32, match_keys6

    cols, valid = batch_cols6(batch6)
    keys = match_keys6(cols, ruleset6.rules6, ruleset6.deny_key, rule_block)
    return _update_registers(
        state, keys, valid, fold_src32(cols), cols["acl"] | V6_ACL_TAG,
        n_keys=n_keys, topk_k=topk_k, exact_counts=exact_counts, salt=salt,
        topk_sample_shift=topk_sample_shift, counts_impl=counts_impl,
        update_impl=update_impl, topk_every=topk_every,
    )


class DeviceRulesetStacked(NamedTuple):
    """Device-resident stacked rule slabs (BASELINE.json config #4)."""

    rules3d: jax.Array  # [G, Rmax, RULE_COLS] uint32
    deny_key: jax.Array  # [n_acls] uint32


class DeviceRulesetTenant(NamedTuple):
    """Device-resident TENANT-stacked rule tensors (one packing bucket).

    Many tenants' independently-packed rulesets, each padded to the
    bucket's rule/ACL rungs (runtime/tenancy.py ladder) and stacked on a
    leading tenant axis.  Each tenant keeps its OWN key/gid universe —
    the step dynamically slices one tenant's plane out, runs the
    unchanged flat core, and writes the plane back, so per-tenant
    registers are bit-identical to a solo run of that tenant.
    """

    rules_t: jax.Array  # [T, R_pad, RULE_COLS] uint32, R_pad % rule_block == 0
    deny_key_t: jax.Array  # [T, A_pad] uint32


def ship_ruleset_stacked(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> DeviceRulesetStacked:
    from ..hostside.pack import stack_rules

    return DeviceRulesetStacked(
        rules3d=jnp.asarray(stack_rules(packed, rule_block)),
        deny_key=jnp.asarray(packed.deny_key.astype(np.uint32)),
    )


def analysis_step_stacked(
    state: AnalysisState,
    ruleset: DeviceRulesetStacked,
    batch: jax.Array,  # [G, TUPLE_COLS, Bg] uint32, grouped by ACL gid
    *,
    n_keys: int,
    topk_k: int,
    exact_counts: bool = True,
    rule_block: int = RULE_BLOCK,
    salt: jax.Array | int = 0,
    topk_sample_shift: int = 0,
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    """Grouped-batch variant of analysis_step (vmap over rule slabs).

    The match runs per-group against only that ACL's slab; the mergeable
    register updates are order-invariant, so the resulting state is
    identical to the flat step fed the same multiset of lines.
    """
    cols, valid = batch_cols(batch)
    keys = match_keys_stacked(cols, ruleset.rules3d, ruleset.deny_key, rule_block).reshape(-1)
    return _update_registers(
        state,
        keys,
        valid.reshape(-1),
        cols["src"].reshape(-1),
        cols["acl"].reshape(-1),
        n_keys=n_keys,
        topk_k=topk_k,
        exact_counts=exact_counts,
        salt=salt,
        topk_sample_shift=topk_sample_shift,
        update_impl=update_impl,
        topk_every=topk_every,
    )


def state_to_host(state: AnalysisState) -> dict[str, np.ndarray]:
    """Fetch every register file to host numpy (a hard sync point)."""
    return {
        k: np.asarray(jax.device_get(getattr(state, k)))
        for k in AnalysisState._fields
    }


def counts_total(state: AnalysisState) -> int:
    """Total hits across all keys, fetched to host — and therefore a hard
    synchronization point.

    ``jax.block_until_ready`` is not a reliable barrier on every PJRT
    plugin (the remote-tunnel plugin used in development returns
    immediately for shard_map outputs); a device_get of a register is: no
    bytes can arrive before every step that wrote them has executed.
    Benchmarks close their timed sections with this and assert the delta
    equals the number of valid lines stepped (each valid line contributes
    exactly one count — a rule key or its ACL's implicit deny).
    """
    lo = np.asarray(jax.device_get(state.counts_lo), dtype=np.uint64)
    hi = np.asarray(jax.device_get(state.counts_hi), dtype=np.uint64)
    return int((lo + (hi << np.uint64(32))).sum())


def sync_state(state: AnalysisState) -> None:
    """Force completion of every pending step writing into ``state``.

    See :func:`counts_total` for why this is a device_get rather than
    ``jax.block_until_ready``; the fetched register is small ([n_keys]
    uint32), so the transfer cost is negligible.
    """
    np.asarray(jax.device_get(state.counts_lo))


# ---------------------------------------------------------------------------
# Finalize: device registers -> report-shaped host results.
# ---------------------------------------------------------------------------


def finalize(
    state: AnalysisState,
    packed: PackedRuleset,
    cfg: AnalysisConfig,
    tracker: topk_ops.TopKTracker | None = None,
    *,
    topk: int = 10,
    totals: dict | None = None,
    v6_digests: dict[int, int] | None = None,
):
    """Pull registers to host and assemble the Report (SURVEY.md L5).

    ``v6_digests`` maps fold_src32 digests -> 128-bit source ints (built
    by the stream driver as it packs v6 lines, bounded) so v6 talkers
    render as real addresses; digests missing from the map (map capped,
    or resume discarded pre-crash entries) render as ``v6#<8 hex>``.
    """
    from ..hostside.aclparse import int_to_ip6
    from ..runtime.report import build_report

    lo = np.asarray(jax.device_get(state.counts_lo))
    hi = np.asarray(jax.device_get(state.counts_hi))
    hll_regs = np.asarray(jax.device_get(state.hll))
    cms_host = np.asarray(jax.device_get(state.cms))

    if cfg.exact_counts:
        per_key = count_ops.to_u64(lo, hi)
    else:
        per_key = cms_ops.cms_query_np(cms_host, np.arange(packed.n_keys, dtype=np.uint32))
    card = hll_ops.hll_estimate_np(hll_regs)

    hits = {}
    uniq = {}
    for key_id, meta in enumerate(packed.key_meta):
        k = (meta.firewall, meta.acl, meta.index)
        hits[k] = int(per_key[key_id])
        if per_key[key_id] > 0:
            uniq[k] = int(round(card[key_id]))

    # HLL error band (VERDICT Weak #6): a deletion report quoting unique
    # sources without its ±1.04/sqrt(m) p90 band invites over-trust.  The
    # band and (when the observed key space sits far below the sketch's
    # size) a concrete --hll-p memory hint ride totals so every renderer
    # — text, JSON, the serve endpoints — can surface them.
    totals = dict(totals or {})
    m = cfg.sketch.hll_m
    hll_info: dict = {
        "p": cfg.sketch.hll_p,
        "m": m,
        "rel_err_p90": round(1.04 / (m ** 0.5), 4),
    }
    u_max = max(uniq.values(), default=0)
    if u_max and u_max * 8 <= m and cfg.sketch.hll_p > 4:
        import math

        fit_p = max(4, math.ceil(math.log2(max(8 * u_max, 16))))
        if fit_p < cfg.sketch.hll_p:
            hll_info["hint"] = (
                f"observed per-rule cardinality tops out at ~{u_max}, far "
                f"below the hll_p={cfg.sketch.hll_p} sketch ({m} registers/"
                f"rule); --hll-p {fit_p} would cut HLL register memory "
                f"{2 ** (cfg.sketch.hll_p - fit_p)}x at ±"
                f"{100 * 1.04 / (2 ** fit_p) ** 0.5:.1f}% p90 error"
            )
    totals["hll"] = hll_info

    talkers = None
    if tracker is not None:
        gid_to_name = {gid: name for name, gid in packed.acl_gid.items()}
        talkers = {}
        tag = int(V6_ACL_TAG)
        for gid in tracker.acls():
            is6 = bool(int(gid) & tag)
            name = gid_to_name.get(int(gid) & ~tag)
            if name is None:
                continue
            items = tracker.top(gid, topk)
            if is6:
                dig = v6_digests or {}
                items = [
                    (
                        int_to_ip6(dig[int(s)])
                        if int(s) in dig
                        else f"v6#{int(s):08x}",
                        c,
                    )
                    for s, c in items
                ]
            talkers.setdefault(name, []).extend(items)
        # one merged per-ACL section across families, ranked by count
        talkers = {
            k: sorted(v, key=lambda kv: -kv[1])[:topk]
            for k, v in talkers.items()
        }

    return build_report(
        packed,
        hits,
        backend="tpu",
        totals=totals,
        unique_sources=uniq,
        talkers=talkers,
    )
