"""The flagship analysis pipeline: device state + one fused jitted step."""
