"""Scale-out: device meshes, shard_map'd steps, XLA collectives over ICI/DCN.

The reference's entire distributed substrate is the Hadoop shuffle —
mappers spill partitioned key/count pairs, reducers pull and merge-sort
(SURVEY.md §3c).  Here the same dataflow is two XLA collectives on
mergeable registers: ``psum`` for additive state (exact counts, CMS),
``pmax`` for HLL registers — riding ICI within a pod and the DCN mesh axis
across hosts, with no serialization, sorting, or disk in between.
"""
