"""Mesh construction and batch sharding helpers.

One logical axis, ``data``: log lines are independent records (SURVEY.md
§3b — data parallelism is the reference's single strategy), so the batch
axis shards across every chip and all state stays replicated.  The code is
mesh-generic: the same program runs on 1 chip, a v5e-8's 8 chips, or a
multi-host DCN×ICI mesh (see distributed.py) without modification.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime import faults


def make_mesh(devices: list | None = None, axis: str = "data") -> Mesh:
    devs = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devs), (axis,))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Column-major [TUPLE_COLS, B] batches shard along B."""
    return NamedSharding(mesh, P(None, axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch_np: np.ndarray, axis: str = "data") -> jax.Array:
    """Host [TUPLE_COLS, B] -> device array sharded over the data axis."""
    # chaos site: H2D transfer failure.  Reached from both the sync chunk
    # loop and the prefetch producer's pack closure, so one site exercises
    # both propagation paths (direct raise vs. typed re-raise at consume).
    faults.fire("stream.device_put.fail")
    return jax.device_put(batch_np, batch_sharding(mesh, axis))


def shard_grouped(mesh: Mesh, grouped_np: np.ndarray, axis: str = "data") -> jax.Array:
    """Host [G, TUPLE_COLS, lane] -> device array, lane axis sharded."""
    faults.fire("stream.device_put.fail")
    return jax.device_put(grouped_np, NamedSharding(mesh, P(None, None, axis)))


def pad_batch_size(batch_size: int, mesh: Mesh, axis: str = "data") -> int:
    """Round batch_size up to a multiple of the data-axis size."""
    n = mesh.shape[axis]
    return ((batch_size + n - 1) // n) * n
