"""Mesh construction and batch sharding helpers.

Log lines are independent records (SURVEY.md §3b — data parallelism is
the reference's single strategy), so the batch axis shards across every
chip and all state stays replicated.  Two topologies:

- **flat** (the historical shape): one logical ``data`` axis over every
  device.
- **hybrid**: the two-level DCN x ICI idiom (SNIPPETS.md [2],
  ``jax.experimental.mesh_utils.create_hybrid_device_mesh``): an outer
  ``dcn`` axis of host-sized groups times an inner ICI axis.  Batches
  shard over BOTH axes and every register merge reduces over both, so
  the device-to-slice mapping — and therefore every report — is
  bit-identical to the flat mesh over the same devices (pinned on CPU as
  2x4 vs flat 8, tests/test_autoscale.py).  This is how world size grows
  past one host: the outer axis is the between-host (DCN) dimension the
  autoscaler will add hosts along, while within-host merges stay on ICI.

The code is mesh-generic either way: helpers derive the batch axes from
the mesh itself, so the same program runs on 1 chip, a v5e-8's 8 chips,
or a multi-host DCN x ICI mesh without modification.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..errors import AnalysisError
from ..runtime import faults, retrypolicy

#: Outer (between-host) axis name of the hybrid topology.
DCN_AXIS = "dcn"


def make_mesh(
    devices: list | None = None,
    axis: str = "data",
    *,
    topology: str = "flat",
    dcn: int = 0,
) -> Mesh:
    """Build the device mesh for one process's drivers.

    ``topology="hybrid"`` arranges the devices as a ``[dcn, ici]``
    2-level mesh (axes ``("dcn", axis)``).  ``dcn=0`` auto-sizes the
    outer axis: the process count when multi-process (one group per
    host — the ``create_hybrid_device_mesh`` granule), else 2 (the CPU
    exercise geometry).  Device order is preserved (row-major reshape),
    which is what keeps batch slice placement identical to the flat
    mesh.

    ``serve --distributed`` (runtime/distserve.py, DESIGN §22) realizes
    the hybrid topology ACROSS processes instead of within one: each
    ingest host runs its own flat mesh (this function's ``topology=
    "flat"`` over its local devices — the inner ICI axis), and the
    outer ``dcn`` axis becomes the host tier itself, reduced host-side
    at rank 0 under the same associative merge laws the in-mesh
    ``("dcn", data)`` collective would apply.  That trade is deliberate:
    a dead host degrades the merge (typed, named, recoverable) instead
    of poisoning a pending cross-host collective.
    """
    devs = np.asarray(devices if devices is not None else jax.devices())
    if topology == "flat":
        return Mesh(devs, (axis,))
    if topology != "hybrid":
        raise AnalysisError(f"unknown mesh topology {topology!r}")
    n = devs.size
    if dcn == 0:
        dcn = jax.process_count() if jax.process_count() > 1 else 2
    if dcn < 2:
        raise AnalysisError(
            f"hybrid mesh needs an outer (dcn) extent >= 2, got {dcn}"
        )
    if n % dcn:
        raise AnalysisError(
            f"hybrid mesh: {n} devices do not divide into {dcn} dcn groups"
            " (pass --mesh-dcn that divides the device count)"
        )
    return Mesh(devs.reshape(dcn, n // dcn), (DCN_AXIS, axis))


def data_axes(mesh: Mesh, axis: str = "data") -> str | tuple[str, ...]:
    """The batch axes of ``mesh``: every mesh axis (flat: just ``axis``).

    Returned in PartitionSpec/collective form — a bare name for the flat
    mesh, the ``("dcn", data)`` tuple for the hybrid one — so callers
    thread one value through ``P(None, axes)`` and ``lax.psum(x, axes)``
    alike.
    """
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


def data_extent(mesh: Mesh) -> int:
    """Total batch-parallel width (product of every mesh axis extent)."""
    return int(math.prod(mesh.shape.values()))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Column-major [TUPLE_COLS, B] batches shard along B (all axes)."""
    return NamedSharding(mesh, P(None, data_axes(mesh, axis)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch_np: np.ndarray, axis: str = "data") -> jax.Array:
    """Host [TUPLE_COLS, B] -> device array sharded over the data axes."""

    # chaos site: H2D transfer failure.  Reached from both the sync chunk
    # loop and the prefetch producer's pack closure, so one site exercises
    # both propagation paths (direct raise vs. typed re-raise at consume).
    # The device_put retry policy wraps the whole attempt: a transient
    # runtime fault (k consecutive injected fires, a recoverable XLA
    # status) re-issues the transfer with seeded backoff; exhaustion
    # escalates the original typed error unchanged.
    def _put():
        faults.fire("stream.device_put.fail")
        return jax.device_put(batch_np, batch_sharding(mesh, axis))

    return retrypolicy.call("device_put", _put)


def shard_grouped(mesh: Mesh, grouped_np: np.ndarray, axis: str = "data") -> jax.Array:
    """Host [G, TUPLE_COLS, lane] -> device array, lane axis sharded."""

    def _put():
        faults.fire("stream.device_put.fail")
        return jax.device_put(
            grouped_np,
            NamedSharding(mesh, P(None, None, data_axes(mesh, axis))),
        )

    return retrypolicy.call("device_put", _put)


def shard_ring_batch(mesh: Mesh, ring_batch, axis: str = "data") -> jax.Array:
    """Per-chip ring views -> ONE sharded device array, chip by chip.

    The ring feeder (hostside.feeder.RingFeeder) hands each device's
    ``[TUPLE_COLS, shard_rows]`` plane as a zero-copy view into that
    chip's shared-memory ring slot.  Each view bit-packs to the 16 B/row
    wire layout (a copy out of the slot — the slots release right after)
    and ``device_put``s straight to ITS device; the global array is then
    assembled from the per-device shards with no host-side concatenation
    — the whole-batch copy + single global ``device_put`` the queue tier
    pays disappears.  The resulting array carries the exact sharding
    ``shard_batch`` would produce, so the compiled step is byte-for-byte
    the same program.
    """
    from ..hostside import pack as pack_mod

    sharding = batch_sharding(mesh, axis)
    wires = [pack_mod.compact_batch(v) for v in ring_batch.views]
    ring_batch.release()  # compact_batch copied out of the shm slots
    cols = wires[0].shape[0]
    shard_w = wires[0].shape[1]
    global_shape = (cols, shard_w * len(wires))

    # retry wraps the per-chip transfer fan-out as one unit: the wires
    # are host copies (the shm slots are already released), so a second
    # attempt re-issues every device_put safely
    def _put():
        faults.fire("stream.device_put.fail")
        arrs = []
        for dev, idx in sharding.devices_indices_map(global_shape).items():
            col = idx[1]
            start = 0 if col.start is None else int(col.start)
            arrs.append(jax.device_put(wires[start // shard_w], dev))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, arrs
        )

    return retrypolicy.call("device_put", _put)


def pad_batch_size(batch_size: int, mesh: Mesh, axis: str = "data") -> int:
    """Round batch_size up to a multiple of the total data width."""
    n = data_extent(mesh)
    return ((batch_size + n - 1) // n) * n
