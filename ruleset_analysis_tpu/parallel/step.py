"""The data-parallel analysis step: shard_map + explicit ICI collectives.

Per chunk, each device runs the single-device hot path on its batch shard
and produces *delta* registers from zero; the deltas then merge with one
collective each — ``psum`` for counts/CMS (addition is the merge law),
``pmax`` for HLL (max is the merge law) — and fold into the replicated
state.  This is the exact seam BASELINE.json's north star names: the
Hadoop shuffle/sort/merge replaced by two XLA collectives over ICI.

Integer adds are associative and commutative, so the merged state is
bit-identical to a single-device run over the concatenated batch — the
property tests/test_parallel.py asserts (SURVEY.md §5 "multi-node without
a cluster"), and what makes resume-by-re-merge idempotent.

shard_map (not GSPMD auto-sharding) because the collective placement here
is the design: scatter locally into small replicated registers, reduce the
registers — never all-gather the (huge) batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..config import AnalysisConfig
from ..models.pipeline import (
    AnalysisState, ChunkOut, DeviceRuleset, DeviceRuleset6,
    DeviceRulesetStacked, DeviceRulesetTenant, V6_ACL_TAG,
    batch_cols, batch_cols6,
)
from ..ops import cms as cms_ops
from ..ops import counts as count_ops
from ..ops import hll as hll_ops
from ..ops import topk as topk_ops
from ..ops.match import RULE_BLOCK, match_keys, match_keys_stacked
from ..runtime import devprof

_U32 = jnp.uint32


def _shard_map(f, *, mesh, in_specs, out_specs):
    """Version-portable shard_map with replication checking off.

    ``jax.shard_map`` (kwarg ``check_vma``) landed after 0.4.x; older
    installs ship ``jax.experimental.shard_map`` (kwarg ``check_rep``).
    Both compile the identical program here — the collectives are written
    explicitly, so the replication checker adds nothing but version skew.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as sm_exp

    return sm_exp(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def _merge_tail(
    state: AnalysisState,
    keys: jax.Array,  # [b] u32 count keys, local shard
    valid: jax.Array,  # [b] u32 WEIGHT plane (0 = invalid; a coalesced
    #                    row's w counts as w raw lines — every update
    #                    below is weight-linear or idempotent, DESIGN §11)
    src: jax.Array,  # [b] u32
    acl: jax.Array,  # [b] u32
    salt: jax.Array,
    *,
    axis: str,
    n_keys: int,
    topk_k: int,
    exact_counts: bool,
    topk_sample_shift: int = 0,
    counts_delta: jax.Array | None = None,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    # The register-update tail shared by the flat and stacked shard steps:
    # mirrors pipeline._update_registers with the collective merges
    # interleaved at the law-of-merge seams (psum for adds, pmax for max);
    # tests/test_parallel.py pins it bit-identical to the single-device
    # step over the concatenated batch.

    # one globally-merged bincount feeds exact counts AND the per-rule CMS
    # (linear in per-key increments — see pipeline._update_registers);
    # the batch-sized CMS scatter this replaces dominated the shard step.
    # counts_delta: the fused pallas kernel already built the local
    # bincount in VMEM (ops/pallas_fused.py) — skip the batch-sized
    # scatter and merge its row-sized result instead.
    # Stage boundaries carry jax.named_scope labels (ra.counts/ra.cms/
    # ra.hll inside the ops; ra.talk/ra.merge here) so profiler fusions
    # attribute to semantic stages instead of fusion.N — the substrate
    # runtime/devprof.py classifies (DESIGN §14).  Trace-time only.
    #
    # update_impl="sorted": local deltas come from the sorted
    # segment-reduce formulations (ops/sorted_update.py, DESIGN §15);
    # the collective merge seams are IDENTICAL — only how each shard
    # builds its delta changes, so bit-identity to the scatter path
    # follows from per-shard value identity plus the same merges.
    if update_impl == "sorted":
        from ..ops import sorted_update as sorted_ops

        need = counts_delta is None and counts_impl == "scatter"
        sorted_delta, delta_hll = sorted_ops.counts_hll_sorted(
            jnp.zeros_like(state.hll), keys, valid, src, n_keys,
            need_counts=need,
        )
        if counts_delta is None:
            counts_delta = (
                sorted_delta
                if need
                else count_ops.SEGMENT_COUNTS_IMPLS[counts_impl](
                    keys, valid, n_keys
                )
            )
    else:
        if counts_delta is None:
            counts_delta = count_ops.SEGMENT_COUNTS_IMPLS[counts_impl](
                keys, valid, n_keys
            )
        delta_hll = hll_ops.hll_update(
            jnp.zeros_like(state.hll), keys, src, valid
        )
    with jax.named_scope("ra.merge"):
        delta = lax.psum(counts_delta, axis)
    if exact_counts:
        lo, hi = count_ops.add64(state.counts_lo, state.counts_hi, delta)
    else:
        lo, hi = state.counts_lo, state.counts_hi
    cms = cms_ops.cms_update(state.cms, jnp.arange(n_keys, dtype=_U32), delta)

    with jax.named_scope("ra.merge"):
        hll = jnp.maximum(state.hll, lax.pmax(delta_hll, axis))

    dt, wt = state.talk_cms.shape
    if update_impl == "sorted":
        from ..ops import sorted_update as sorted_ops

        def _tables(sel):
            return sorted_ops.talker_tables_sorted(
                acl, src, valid, salt, width=wt, depth=dt,
                slots=topk_ops.CAND_SLOTS, sample_shift=topk_sample_shift,
                with_candidates=sel,
            )

        if topk_every > 1:
            delta_talk, cnt, rep = lax.cond(
                salt % _U32(topk_every) == _U32(0),
                lambda _: _tables(True),
                lambda _: _tables(False),
                None,
            )
        else:
            delta_talk, cnt, rep = _tables(True)
        with jax.named_scope("ra.merge"):
            talk_cms = state.talk_cms + lax.psum(delta_talk, axis)
        s_acl, s_src, _sv = topk_ops.sample_cols(
            acl, src, valid, salt, topk_sample_shift
        )
        ca, cs, ce = topk_ops.select_from_tables(
            cnt, rep, s_acl, s_src, talk_cms,
            min(topk_k, s_acl.shape[0]),
        )
    else:
        with jax.named_scope("ra.talk"):
            delta_talk = cms_ops.cms_update(
                jnp.zeros((dt, wt), _U32), topk_ops.hash_pair(acl, src), valid
            )
        with jax.named_scope("ra.merge"):
            talk_cms = state.talk_cms + lax.psum(delta_talk, axis)
        # candidate selection against the *merged* global talker sketch,
        # then gather every device's candidates so the host sees them all,
        # replicated (sample_shift: salt-rotated sampled selection — the
        # sketch covered every line above; see ops.topk.select_candidates;
        # topk_every: deferred selection, ops.topk.maybe_select)
        k1 = min(topk_k, valid.shape[0])
        ca, cs, ce = topk_ops.maybe_select(
            lambda _: topk_ops.select_candidates(
                talk_cms, acl, src, valid, k1,
                salt=salt, sample_shift=topk_sample_shift,
            ),
            salt, topk_every,
            topk_ops.cand_k(k1, valid.shape[0], topk_sample_shift),
        )
    with jax.named_scope("ra.merge"):
        cand_acl = lax.all_gather(ca, axis, tiled=True)
        cand_src = lax.all_gather(cs, axis, tiled=True)
        cand_est = lax.all_gather(ce, axis, tiled=True)

    return (
        AnalysisState(counts_lo=lo, counts_hi=hi, cms=cms, hll=hll, talk_cms=talk_cms),
        ChunkOut(cand_acl=cand_acl, cand_src=cand_src, cand_est=cand_est),
    )


def _core_flat(
    state: AnalysisState,
    ruleset: DeviceRuleset,
    cols: dict,  # unpacked field columns (batch_cols)
    valid: jax.Array,  # [b] u32 weight plane
    salt: jax.Array,  # u32 scalar (chunk counter), replicated
    *,
    axis: str,
    n_keys: int,
    topk_k: int,
    exact_counts: bool,
    rule_block: int,
    match_impl: str = "xla",
    topk_sample_shift: int = 0,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    # The post-unpack body of the flat shard step.  Split from the
    # batch unpack so the static lint plane (verify/, DESIGN §18) can
    # trace the SHIPPING program with the weight plane as an explicit
    # jaxpr input — the taint source of the weight-linearity proof —
    # instead of a slice of the packed batch.  One definition: the real
    # step and the linter trace this exact function.
    counts_delta = None
    if match_impl == "pallas_fused" and ruleset.rules_fm is not None:
        from ..ops import pallas_fused

        keys, counts_delta = pallas_fused.match_keys_and_counts_pallas(
            cols, valid, ruleset.rules, ruleset.rules_fm, ruleset.deny_key,
            n_keys,
        )
    elif match_impl == "pallas" and ruleset.rules_fm is not None:
        from ..ops import pallas_match

        keys = pallas_match.match_keys_pallas(
            cols, ruleset.rules, ruleset.rules_fm, ruleset.deny_key
        )
    else:
        keys = match_keys(cols, ruleset.rules, ruleset.deny_key, rule_block)
    return _merge_tail(
        state, keys, valid, cols["src"], cols["acl"], salt,
        axis=axis, n_keys=n_keys, topk_k=topk_k, exact_counts=exact_counts,
        topk_sample_shift=topk_sample_shift, counts_delta=counts_delta,
        counts_impl=counts_impl, update_impl=update_impl,
        topk_every=topk_every,
    )


def _local_shard_step(
    state: AnalysisState,
    ruleset: DeviceRuleset,
    batch: jax.Array,  # [TUPLE_COLS or WIRE_COLS, B/n] local shard
    salt: jax.Array,  # u32 scalar (chunk counter), replicated
    **kw,
) -> tuple[AnalysisState, ChunkOut]:
    cols, valid = batch_cols(batch)
    return _core_flat(state, ruleset, cols, valid, salt, **kw)


def _core_stacked(
    state: AnalysisState,
    ruleset: DeviceRulesetStacked,
    cols: dict,  # grouped field columns [G, lane/n]
    valid: jax.Array,  # [G, lane/n] u32 weight plane
    salt: jax.Array,
    *,
    axis: str,
    n_keys: int,
    topk_k: int,
    exact_counts: bool,
    rule_block: int,
    topk_sample_shift: int = 0,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    # Grouped twin of _core_flat: each line scans only its own ACL's
    # slab (vmapped match over the group axis); the mergeable register
    # tail — and therefore the final report — is identical.
    keys = match_keys_stacked(cols, ruleset.rules3d, ruleset.deny_key, rule_block).reshape(-1)
    return _merge_tail(
        state,
        keys,
        valid.reshape(-1),
        cols["src"].reshape(-1),
        cols["acl"].reshape(-1),
        salt,
        axis=axis,
        n_keys=n_keys,
        topk_k=topk_k,
        exact_counts=exact_counts,
        topk_sample_shift=topk_sample_shift,
        counts_impl=counts_impl,
        update_impl=update_impl,
        topk_every=topk_every,
    )


def _local_shard_step_stacked(
    state: AnalysisState,
    ruleset: DeviceRulesetStacked,
    batch: jax.Array,  # [G, TUPLE_COLS or WIRE_COLS, lane/n] local shard
    salt: jax.Array,
    **kw,
) -> tuple[AnalysisState, ChunkOut]:
    cols, valid = batch_cols(batch)
    return _core_stacked(state, ruleset, cols, valid, salt, **kw)


def _core6(
    state: AnalysisState,
    ruleset6: DeviceRuleset6,
    cols: dict,  # unpacked v6 field columns (batch_cols6)
    valid: jax.Array,  # [b] u32 weight plane
    salt: jax.Array,
    *,
    axis: str,
    n_keys: int,
    topk_k: int,
    exact_counts: bool,
    rule_block: int,
    topk_sample_shift: int = 0,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    # IPv6 twin of _core_flat: lexicographic limb match, then the SAME
    # mergeable register tail into the shared key universe.  Source
    # identity for HLL/talkers is the 32-bit limb digest; the talker ACL
    # gid carries V6_ACL_TAG so digests never merge with v4 addresses.
    from ..ops.match6 import fold_src32, match_keys6

    keys = match_keys6(cols, ruleset6.rules6, ruleset6.deny_key, rule_block)
    return _merge_tail(
        state, keys, valid, fold_src32(cols),
        cols["acl"] | jnp.uint32(V6_ACL_TAG), salt,
        axis=axis, n_keys=n_keys, topk_k=topk_k, exact_counts=exact_counts,
        topk_sample_shift=topk_sample_shift, counts_impl=counts_impl,
        update_impl=update_impl, topk_every=topk_every,
    )


def _local_shard_step6(
    state: AnalysisState,
    ruleset6: DeviceRuleset6,
    batch: jax.Array,  # [TUPLE6_COLS, B6/n] local shard
    salt: jax.Array,
    **kw,
) -> tuple[AnalysisState, ChunkOut]:
    cols, valid = batch_cols6(batch)
    return _core6(state, ruleset6, cols, valid, salt, **kw)


def _core_tenant(
    state: AnalysisState,  # leaves carry a leading [T] tenant axis
    ruleset: DeviceRulesetTenant,
    cols: dict,  # unpacked field columns (batch_cols) — ONE tenant's lines
    valid: jax.Array,  # [b] u32 weight plane
    tid: jax.Array,  # i32 scalar tenant index into the bucket stack
    salt: jax.Array,  # u32 scalar (per-tenant chunk counter), replicated
    *,
    axis: str,
    n_keys: int,  # the BUCKET's padded key universe (R_pad + A_pad)
    topk_k: int,
    exact_counts: bool,
    rule_block: int,
    topk_sample_shift: int = 0,
    counts_impl: str = "scatter",
    update_impl: str = "scatter",
    topk_every: int = 1,
) -> tuple[AnalysisState, ChunkOut]:
    # Tenant-sliced twin of _core_flat (ISSUE 16): every register plane
    # carries a leading tenant axis; the step dynamically slices tenant
    # `tid`'s plane + rule tensor out of the bucket stack, runs the
    # UNCHANGED flat core on it, and scatters the plane back.  The merge
    # laws are untouched (the collectives act on the sliced plane), so a
    # tenant's slice evolves bit-identically to a solo run with the same
    # chunk boundaries and salts — the tenancy property test pins it.
    # dynamic_slice is not a scope-required primitive in the jaxpr lint
    # plane, and the weight plane threads through _core_flat verbatim,
    # so the tenant programs prove weight-linear exactly like flat ones.
    with jax.named_scope("ra.tenant_slice"):
        rules = lax.dynamic_index_in_dim(ruleset.rules_t, tid, 0, keepdims=False)
        deny = lax.dynamic_index_in_dim(ruleset.deny_key_t, tid, 0, keepdims=False)
        plane = AnalysisState(*(
            lax.dynamic_index_in_dim(x, tid, 0, keepdims=False) for x in state
        ))
    plane, out = _core_flat(
        plane, DeviceRuleset(rules=rules, deny_key=deny, rules_fm=None),
        cols, valid, salt,
        axis=axis, n_keys=n_keys, topk_k=topk_k, exact_counts=exact_counts,
        rule_block=rule_block, match_impl="xla",
        topk_sample_shift=topk_sample_shift, counts_impl=counts_impl,
        update_impl=update_impl, topk_every=topk_every,
    )
    with jax.named_scope("ra.tenant_unslice"):
        new_state = AnalysisState(*(
            lax.dynamic_update_index_in_dim(big, small, tid, 0)
            for big, small in zip(state, plane)
        ))
    return new_state, out


def _local_shard_step_tenant(
    state: AnalysisState,
    ruleset: DeviceRulesetTenant,
    batch: jax.Array,  # [TUPLE_COLS or WIRE_COLS, B/n] local shard
    tid: jax.Array,  # i32 scalar, replicated
    salt: jax.Array,  # u32 scalar, replicated
    **kw,
) -> tuple[AnalysisState, ChunkOut]:
    cols, valid = batch_cols(batch)
    return _core_tenant(state, ruleset, cols, valid, tid, salt, **kw)


#: Post-unpack shard-step bodies by program kind — what the static lint
#: plane traces (verify/grid.py).  The shipping steps above are thin
#: unpack wrappers around exactly these functions, so a lint verdict on
#: a core IS a verdict on the shipping program.
CORES = {
    "flat": _core_flat,
    "stacked": _core_stacked,
    "v6": _core6,
    "tenant": _core_tenant,
}


#: Bake the rule tensor into the compiled step as an XLA constant when it
#: is at most this many bytes.  The ruleset is fixed for a whole stream,
#: and constant rules let XLA specialize the [B, R] predicate evaluation —
#: measured ~2x the whole fused step vs passing rules as a traced argument
#: (bench_suite.py stage).  Above the threshold the generic argument path
#: keeps compile time and HLO size bounded for pathological rulesets.
RULES_CONST_MAX_BYTES = 8 << 20


def _rules_nbytes(ruleset) -> int:
    return sum(
        x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(ruleset)
    )


#: Distinct specialized executables kept per step builder.  Real drivers
#: use one ruleset per stream; the bound only guards against a caller that
#: cycles many DIFFERENT rulesets through one step (each executable pins
#: its baked-in rules, so an unbounded cache would leak).
_SPECIALIZED_CACHE_MAX = 4


def _make_step(mesh: Mesh, local, batch_spec, label: str = "step"):
    """Shared builder: ruleset-specialized jits with a generic fallback.

    Returns ``step(state, ruleset, batch, salt)``.  For each distinct
    (small) ruleset VALUE, a jit closing over the ruleset is built once
    and cached — the rule tensor compiles as an XLA constant.  The cache
    is two-level: object identity first (zero-cost for the normal
    one-ruleset stream), then a content fingerprint — so a caller that
    re-ships an equal-valued ruleset per call pays one hash, never a
    recompile.  Oversized rulesets fall back to one generic jit with the
    ruleset as a traced argument (the pre-round-4 behavior).  Results are
    bit-identical either way; only specialization differs.

    Every dispatch passes through the device attribution plane's seam
    (``devprof.active_capture()``): disarmed cost is one module-global
    None-check; armed, the capture window counts dispatches, brackets
    the ``jax.profiler`` trace, and remembers each program's jit +
    abstract arguments so its optimized HLO can be re-derived for
    semantic attribution (runtime/devprof.py, DESIGN §14).  ``label``
    names the program (``step.flat`` / ``step.v6`` / ``step.stacked``)
    in the capture summary.
    """
    generic = None
    by_id: dict[tuple, tuple] = {}  # id-key -> (fingerprint, pinned leaves)
    by_value: dict[str, object] = {}

    def _fingerprint(ruleset) -> str:
        import hashlib

        h = hashlib.sha1()
        for x in jax.tree_util.tree_leaves(ruleset):
            h.update(str(x.shape).encode())
            h.update(np.asarray(x).tobytes())
        return h.hexdigest()

    def step(state, ruleset, batch, salt: int | jax.Array = 0):
        nonlocal generic
        salt = jnp.asarray(salt, dtype=_U32)
        if _rules_nbytes(ruleset) <= RULES_CONST_MAX_BYTES:
            leaves = jax.tree_util.tree_leaves(ruleset)
            id_key = tuple(id(x) for x in leaves)
            hit = by_id.get(id_key)
            if hit is not None:
                fp = hit[0]
            else:
                fp = _fingerprint(ruleset)
                if len(by_id) >= 4 * _SPECIALIZED_CACHE_MAX:
                    by_id.clear()
                # keep the leaves alive alongside the entry: a freed array's
                # id can be recycled by a NEW array, and a stale id->fp hit
                # would silently run the wrong baked-in rules
                by_id[id_key] = (fp, leaves)
            fn = by_value.get(fp)
            if fn is None:
                sharded = _shard_map(
                    lambda st, b, s: local(st, ruleset, b, s),
                    mesh=mesh,
                    in_specs=(P(), batch_spec, P()),
                    out_specs=(P(), P()),
                )
                fn = jax.jit(sharded, donate_argnums=(0,))
                if len(by_value) >= _SPECIALIZED_CACHE_MAX:
                    by_value.pop(next(iter(by_value)))  # evict oldest
                by_value[fp] = fn
            cap = devprof.active_capture()
            if cap is not None:
                return cap.dispatch(label, fn, (state, batch, salt))
            return fn(state, batch, salt)
        if generic is None:
            sharded = _shard_map(
                local,
                mesh=mesh,
                in_specs=(P(), P(), batch_spec, P()),
                out_specs=(P(), P()),
            )
            generic = jax.jit(sharded, donate_argnums=(0,))
        cap = devprof.active_capture()
        if cap is not None:
            return cap.dispatch(label, generic, (state, ruleset, batch, salt))
        return generic(state, ruleset, batch, salt)

    return step


_LOCALS = {
    "flat": _local_shard_step,
    "v6": _local_shard_step6,
    "stacked": _local_shard_step_stacked,
}


def _mesh_axes(mesh: Mesh):
    """Collective/batch axes of ``mesh`` (mesh.data_axes, deferred import:
    parallel/mesh.py imports the runtime fault registry at module load).

    The flat topology contributes its single data axis; the hybrid
    DCN x ICI topology contributes the ``("dcn", data)`` tuple —
    ``lax.psum``/``pmax``/``all_gather`` and ``PartitionSpec`` all
    accept the tuple form, and reducing over both axes is
    associative-identical to the flat reduction over the same devices
    (the bit-identity tests pin it).
    """
    from .mesh import data_axes

    return data_axes(mesh)


@functools.lru_cache(maxsize=16)
def _cached_step(
    kind: str,
    mesh: Mesh,
    axis: str,
    n_keys: int,
    topk_k: int,
    exact_counts: bool,
    rule_block: int,
    match_impl: str | None,
    topk_sample_shift: int,
    counts_impl: str,
    update_impl: str,
    topk_every: int,
):
    """Step builders memoized on their full geometry.

    Every driver run builds its step through here, so a second run with
    the same (mesh, config geometry) in the same process gets the SAME
    step closure back — and therefore hits the jit executable cache
    instead of re-tracing and re-compiling.  This is what makes the
    warm-run-then-measure pattern (bench.py/bench_suite.py) actually
    measure steady state: a fresh closure per run would recompile even
    with identical shapes.  Keyed values are all hashable scalars plus
    the Mesh (hashable by devices + axis names); maxsize bounds the
    specialized-jit pyramids kept alive.
    """
    kwargs = dict(
        axis=axis,
        n_keys=n_keys,
        topk_k=topk_k,
        exact_counts=exact_counts,
        rule_block=rule_block,
        topk_sample_shift=topk_sample_shift,
        counts_impl=counts_impl,
        update_impl=update_impl,
        topk_every=topk_every,
    )
    if match_impl is not None:
        kwargs["match_impl"] = match_impl
    local = functools.partial(_LOCALS[kind], **kwargs)
    spec = P(None, None, axis) if kind == "stacked" else P(None, axis)
    return _make_step(mesh, local, spec, label=f"step.{kind}")


def _warn_experimental_match(match_impl: str) -> None:
    if match_impl == "pallas_fused":
        import sys

        print(
            "WARNING: EXPERIMENTAL match_impl='pallas_fused' enabled — "
            "measured 0.083x vs the default XLA step on TPU (VERDICT r5); "
            "this is a bench/research kernel, not a production path.",
            file=sys.stderr,
            flush=True,
        )


def make_parallel_step(
    mesh: Mesh,
    cfg: AnalysisConfig,
    n_keys: int,
    rule_block: int = RULE_BLOCK,
):
    """Build the jitted data-parallel step for `mesh`.

    state/ruleset replicated, batch sharded on the data axis; the returned
    state and candidates are replicated (identical on every device).
    """
    _warn_experimental_match(cfg.match_impl)
    return _cached_step(
        "flat",
        mesh,
        _mesh_axes(mesh),
        n_keys,
        cfg.sketch.topk_chunk_candidates,
        cfg.exact_counts,
        rule_block,
        cfg.match_impl,
        cfg.sketch.topk_sample_shift,
        cfg.counts_impl,
        cfg.update_impl,
        cfg.sketch.topk_every,
    )


def make_parallel_step6(
    mesh: Mesh,
    cfg: AnalysisConfig,
    n_keys: int,
    rule_block: int = RULE_BLOCK,
):
    """Build the jitted data-parallel IPv6 step for `mesh`.

    Same sharding contract as :func:`make_parallel_step`: state/ruleset
    replicated, v6 batch sharded on the data axis, merged registers and
    candidates replicated.  The v6 and v4 steps update ONE shared state,
    so the driver may interleave them freely (mergeable registers).
    """
    return _cached_step(
        "v6",
        mesh,
        _mesh_axes(mesh),
        n_keys,
        cfg.sketch.topk_chunk_candidates,
        cfg.exact_counts,
        rule_block,
        None,
        cfg.sketch.topk_sample_shift,
        cfg.counts_impl,
        cfg.update_impl,
        cfg.sketch.topk_every,
    )


@functools.lru_cache(maxsize=16)
def _cached_tenant_step(
    mesh: Mesh,
    axis,
    n_keys: int,
    topk_k: int,
    exact_counts: bool,
    rule_block: int,
    topk_sample_shift: int,
    counts_impl: str,
    update_impl: str,
    topk_every: int,
):
    kwargs = dict(
        axis=axis,
        n_keys=n_keys,
        topk_k=topk_k,
        exact_counts=exact_counts,
        rule_block=rule_block,
        topk_sample_shift=topk_sample_shift,
        counts_impl=counts_impl,
        update_impl=update_impl,
        topk_every=topk_every,
    )
    local = functools.partial(_local_shard_step_tenant, **kwargs)
    sharded = _shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), P(None, axis), P(), P()),
        out_specs=(P(), P()),
    )
    jfn = jax.jit(sharded, donate_argnums=(0,))

    def step(state, ruleset, batch, tid: int | jax.Array, salt: int | jax.Array = 0):
        tid = jnp.asarray(tid, dtype=jnp.int32)
        salt = jnp.asarray(salt, dtype=_U32)
        cap = devprof.active_capture()
        if cap is not None:
            return cap.dispatch(
                "step.tenant", jfn, (state, ruleset, batch, tid, salt)
            )
        return jfn(state, ruleset, batch, tid, salt)

    return step


def make_tenant_step(
    mesh: Mesh,
    cfg: AnalysisConfig,
    n_keys: int,
    rule_block: int = RULE_BLOCK,
):
    """Build the jitted multi-tenant step for `mesh` (one packing bucket).

    ``step(state, ruleset, batch, tid, salt)``: tenant-stacked state and
    rule tensors replicated, ONE tenant's batch sharded on the data axis,
    the tenant index ``tid`` a traced scalar.  Deliberately NEVER
    ruleset-specialized (unlike :func:`_make_step`): the rule stack is a
    traced argument, so hot-reloading one tenant — a value change in one
    slice of the stack — reuses the same executable.  Constant-baking
    would force a full recompile of the shared program on every
    single-tenant reload, stalling every other tenant in the bucket,
    which is exactly the isolation guarantee the tenancy plane makes.
    Results are bit-identical either way (see _make_step docstring).
    """
    return _cached_tenant_step(
        mesh,
        _mesh_axes(mesh),
        n_keys,
        cfg.sketch.topk_chunk_candidates,
        cfg.exact_counts,
        rule_block,
        cfg.sketch.topk_sample_shift,
        cfg.counts_impl,
        cfg.update_impl,
        cfg.sketch.topk_every,
    )


def make_parallel_step_stacked(
    mesh: Mesh,
    cfg: AnalysisConfig,
    n_keys: int,
    rule_block: int = RULE_BLOCK,
):
    """Build the jitted data-parallel STACKED step for `mesh`.

    The grouped batch ``[G, TUPLE_COLS, lane]`` shards along the lane
    (per-group line) axis — every device holds a slice of every ACL's
    bucket plus the full (replicated) slab tensor, so the match needs no
    rule-side communication and the register merges are the same two
    collectives as the flat path.  ``lane`` must divide by the mesh size.
    """
    return _cached_step(
        "stacked",
        mesh,
        _mesh_axes(mesh),
        n_keys,
        cfg.sketch.topk_chunk_candidates,
        cfg.exact_counts,
        rule_block,
        None,
        cfg.sketch.topk_sample_shift,
        cfg.counts_impl,
        cfg.update_impl,
        cfg.sketch.topk_every,
    )
