"""Multi-host scale-out: jax.distributed + a (dcn, data) mesh.

The reference scales across machines by letting YARN fork mapper processes
on every node and shuffling over TCP (SURVEY.md §3c).  The TPU-native
equivalent: one process per host joins a ``jax.distributed`` cluster; the
global device mesh then spans hosts, and the SAME shard_map step from
step.py runs unmodified — XLA routes the register merges over ICI within a
pod slice and over DCN between hosts.

Because every collective here reduces *small replicated registers* (not
the batch), the DCN hop costs one latency per chunk, not bandwidth —
the design scales to multi-host exactly like per-pod.

This module is exercised single-host in CI (the fake-device mesh covers
the SPMD program); multi-host init itself needs a real cluster.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    heartbeat_timeout_seconds: int | None = None,
    initialization_timeout: int | None = None,
) -> None:
    """Join (or bootstrap) the multi-host cluster.

    With no arguments, relies on the environment (TPU pod metadata / the
    launcher's JAX_COORDINATOR_* variables), which is how TPU pods
    normally initialize.

    ``heartbeat_timeout_seconds`` bounds dead-peer detection: when a
    process dies mid-job, the coordinator declares it missing after this
    long and every surviving process's pending collective aborts with an
    error instead of hanging — the rebuilt analog of YARN failing a job
    whose task died (SURVEY.md §6 failure detection).  None keeps JAX's
    default (100s).  Older jax releases take no such parameter; it is
    silently dropped there (the elastic supervisor's own stale-heartbeat
    watchdog — runtime/elastic.py — then provides the detection bound,
    which is why recovery stays bounded-time on every supported jax).

    ``initialization_timeout`` bounds cluster FORMATION: a member listed
    in a re-formation plan that dies before joining would otherwise hold
    everyone in initialize() for jax's 300 s default.
    """
    import inspect

    # Cross-process collectives on the CPU backend (the fake-mesh test
    # idiom and any CPU-host deployment) need a CPU collectives library;
    # 0.4.x-era jax defaults to "none" and fails every multi-process
    # computation with "not implemented on the CPU backend".  Newer jax
    # defaults this on (or renames the option) — failures are ignored.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    kw = {}
    if heartbeat_timeout_seconds is not None:
        kw["heartbeat_timeout_seconds"] = heartbeat_timeout_seconds
    if initialization_timeout is not None:
        kw["initialization_timeout"] = initialization_timeout
    supported = inspect.signature(jax.distributed.initialize).parameters
    kw = {k: v for k, v in kw.items() if k in supported}
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kw,
    )


def make_global_mesh(
    axis: str = "data", *, topology: str = "flat", dcn: int = 0
) -> Mesh:
    """The global mesh over every device of every host.

    ``topology="flat"`` (default): one data axis.  A flat axis is
    already correct for register merging — XLA decomposes the global
    psum/pmax into an ICI reduction per pod slice plus a DCN exchange
    between hosts on its own.

    ``topology="hybrid"``: the explicit two-level DCN x ICI mesh
    (SNIPPETS.md [2] ``create_hybrid_device_mesh`` idiom) — an outer
    ``dcn`` axis of ``dcn`` groups (0 = one per process/host) times an
    inner ICI axis; ``jax.devices()`` orders devices by process, so the
    row-major reshape puts each host's devices in one outer group
    exactly as ``create_hybrid_device_mesh`` would.  Batches shard over
    both axes and the register merges reduce over both; reports stay
    bit-identical to the flat mesh (parallel/mesh.py pins the law).
    This is the committed direction for growing world size past one
    host: the outer axis is where the autoscaler adds hosts.
    """
    from . import mesh as mesh_lib

    return mesh_lib.make_mesh(
        list(jax.devices()), axis, topology=topology, dcn=dcn
    )


def local_batch_slice(global_batch_size: int) -> tuple[int, int]:
    """This process's [start, stop) share of each global batch.

    The streaming driver on each host parses only its own slice of the
    input (the analog of HDFS input splits), then forms the global sharded
    array with jax.make_array_from_process_local_data.  Uniform sharding
    requires equal per-process slices, so the global batch size must
    divide evenly (pad_batch_size over the global mesh guarantees a
    device-count multiple; device counts are equal per host on TPU pods).
    """
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"global batch size {global_batch_size} not divisible by "
            f"{n} processes; round it with parallel.mesh.pad_batch_size"
        )
    i = jax.process_index()
    per = global_batch_size // n
    return i * per, (i + 1) * per


def to_global(mesh: Mesh, local_np: np.ndarray, spec) -> jax.Array:
    """Assemble this process's local numpy block into a global jax.Array.

    ``spec`` is the global PartitionSpec; replicated leaves (``P()``) must
    hold identical data on every process (true for the analysis state and
    rule tensor, which every process computes from the same ruleset).
    """
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), np.ascontiguousarray(local_np)
    )


def all_processes_have_data(has_data: bool) -> bool:
    """True while ANY process still has input (one tiny allgather).

    The chunk loop is a collective program: every process must invoke the
    jitted step the same number of times or the job deadlocks.  Processes
    whose input split ran dry keep stepping all-invalid batches until
    every split is exhausted — the register updates are weighted by the
    valid mask, so padding rounds change nothing.
    """
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.asarray([1 if has_data else 0]))
    return bool(np.asarray(flags).sum() > 0)


def value_across_processes(value: int) -> np.ndarray:
    """Every process's value, as a [process_count] array (tiny allgather)."""
    from jax.experimental import multihost_utils

    arr = np.asarray([int(value)], dtype=np.int64)
    return np.asarray(multihost_utils.process_allgather(arr)).reshape(-1)


def allgather_rows(rows: np.ndarray) -> np.ndarray:
    """Concatenate a small per-process [n_i, C] uint32 array across processes.

    ``process_allgather`` needs equal shapes, so row counts gather first
    and each contribution pads to the max before the data gather.  Meant
    for tiny side tables (e.g. v6 talker digest->address rows), not bulk
    data.
    """
    from jax.experimental import multihost_utils

    rows = np.ascontiguousarray(rows, dtype=np.uint32)
    counts = value_across_processes(rows.shape[0])
    m = int(counts.max()) if counts.size else 0
    if m == 0:
        return rows.reshape(0, rows.shape[1] if rows.ndim == 2 else 0)
    padded = np.zeros((m, rows.shape[1]), dtype=np.uint32)
    padded[: rows.shape[0]] = rows
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    if gathered.ndim == 2:
        # some jax versions return the single-process gather UNSTACKED
        # (no leading process axis); normalize to [n_procs, m, C]
        gathered = gathered[None]
    return np.concatenate(
        [gathered[p, : int(counts[p])] for p in range(gathered.shape[0])]
    )


def sum_across_processes(values: dict[str, int]) -> dict[str, int]:
    """Aggregate per-process counters (parsed/skipped/lines) for totals."""
    from jax.experimental import multihost_utils

    keys = sorted(values)
    arr = np.asarray([int(values[k]) for k in keys], dtype=np.int64)
    summed = np.asarray(multihost_utils.process_allgather(arr)).reshape(
        jax.process_count(), len(keys)
    ).sum(axis=0)
    return {k: int(v) for k, v in zip(keys, summed)}


# ---------------------------------------------------------------------------
# Host-tier epoch wire format (runtime/distserve.py, DESIGN §22).
#
# The distributed serve deployment realizes the hybrid mesh's outer
# ("dcn") axis HOST-SIDE: each host accumulates a window into its own
# register planes, and at rotation ships the epoch to rank 0 over a
# control-plane socket (loopback TCP between co-located processes, DCN
# between machines).  A jax.distributed collective would be the obvious
# alternative — but a dead host poisons every surviving peer's pending
# collective, and the serve contract is the opposite: survivors keep
# publishing (degraded, typed WindowIncomplete) when a whole host dies.
# Host-side merge under the proven _merge_tail laws (add64/add32/max)
# keeps the published reports bit-identical to the collective reduction
# AND to a single-host replay of the union of delivered lines, while a
# host's death costs a timeout, never a hang.
# ---------------------------------------------------------------------------


def pack_epoch_payload(
    arrays: dict[str, np.ndarray], extra: dict
) -> bytes:
    """One host's rotated window -> self-delimiting CRC'd wire bytes.

    Layout: ``RAEP1`` magic, u32 JSON length, u32 npz length, u32
    CRC32 over both bodies, JSON (meta/tables/accounting), npz (the
    register arrays).  The CRC catches a torn or interleaved write on
    the host-tier socket the way the WAL and checkpoint planes catch
    torn files — a corrupt epoch must be a typed refusal at the merge
    tier, never silently-wrong published counters.
    """
    import io
    import json as _json
    import struct
    import zlib

    buf = io.BytesIO()
    np.savez(buf, **{k: np.ascontiguousarray(v) for k, v in arrays.items()})
    npz = buf.getvalue()
    meta = _json.dumps(extra, separators=(",", ":")).encode("utf-8")
    crc = zlib.crc32(meta)
    crc = zlib.crc32(npz, crc) & 0xFFFFFFFF
    return (
        b"RAEP1"
        + struct.pack("<III", len(meta), len(npz), crc)
        + meta
        + npz
    )


def unpack_epoch_payload(payload: bytes) -> tuple[dict[str, np.ndarray], dict]:
    """Inverse of :func:`pack_epoch_payload`; typed on any corruption."""
    import io
    import json as _json
    import struct
    import zlib

    from ..errors import AnalysisError

    if len(payload) < 17 or payload[:5] != b"RAEP1":
        raise AnalysisError(
            "host-tier epoch payload lacks the RAEP1 magic (torn frame "
            "or a foreign writer on the merge socket)"
        )
    n_meta, n_npz, crc = struct.unpack("<III", payload[5:17])
    body = payload[17:]
    if len(body) != n_meta + n_npz:
        raise AnalysisError(
            f"host-tier epoch payload truncated: header promises "
            f"{n_meta + n_npz} body bytes, got {len(body)}"
        )
    meta, npz = body[:n_meta], body[n_meta:]
    got = zlib.crc32(npz, zlib.crc32(meta)) & 0xFFFFFFFF
    if got != crc:
        raise AnalysisError(
            f"host-tier epoch payload CRC mismatch (want {crc:#x}, got "
            f"{got:#x}): refusing to merge a corrupt epoch"
        )
    with np.load(io.BytesIO(npz)) as z:
        arrays = {k: z[k] for k in z.files}
    return arrays, _json.loads(meta.decode("utf-8"))
