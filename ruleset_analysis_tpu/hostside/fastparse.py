"""Native fast path for the host parse: ctypes binding to _asaparse.so.

The reference's mapper spends its host CPU in regex parsing (SURVEY.md
§4.3); at TPU-scale feed rates that parse is the end-to-end bottleneck
(SURVEY.md §8.2).  This module loads the C++ parser/packer from
``ruleset_analysis_tpu/native/`` (building it with make/g++ on first use)
and exposes:

- :class:`NativePacker` — drop-in producer of the same column-major
  ``[TUPLE_COLS, B]`` uint32 batches as ``LinePacker.pack_lines(...).T``,
  but straight from raw bytes;
- :func:`batches_from_file` — stream a syslog file (or byte stream) as
  device-ready batches of ``batch_size`` raw lines each.

If no C++ toolchain is available the import still succeeds and
``available()`` returns False; callers fall back to the pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections.abc import Iterator

import numpy as np

from .pack import PackedRuleset, TUPLE_COLS, TUPLE6_COLS

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "_asaparse.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

#: Bytes per read when streaming a file through the native parser.
READ_BLOCK = 8 << 20


def _as_buffer(data: bytes | bytearray | memoryview):
    """ctypes argument for a readable buffer, without copying.

    bytes pass through (immutable, ctypes pins them); bytearray/memoryview
    get a zero-copy ``from_buffer`` view — the caller must drop the
    returned object before resizing the underlying buffer.
    """
    if isinstance(data, bytes):
        return data
    return (ctypes.c_char * len(data)).from_buffer(data)


def host_workers(env_var: str, cap: int) -> int:
    """Worker-count heuristic: ``env_var`` override, else usable cores."""
    env = os.environ.get(env_var)
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    try:
        n = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n = os.cpu_count() or 1
    return max(1, min(n, cap))


def default_parse_threads() -> int:
    """Parse threads for the native path: RA_PARSE_THREADS or CPU count.

    On a one-core host this degenerates to the single-threaded parse; on a
    real accelerator host (a v5e-8 host has dozens of cores) the batch
    splits across workers (SURVEY.md §2 L2 — the input-split analog).
    """
    return host_workers("RA_PARSE_THREADS", 32)


def _build() -> bool:
    try:
        r = subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            capture_output=True,
            text=True,
            timeout=120,
        )
        return r.returncode == 0 and os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # always invoke make: it is a fast no-op when the .so is current,
        # and rebuilds it when asaparse.cpp changed (a stale library would
        # silently miss newer ABI symbols)
        if not _build() and not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)
        except (OSError, AttributeError):
            # AttributeError: a stale .so predating the current ABI with
            # no toolchain to rebuild — fall back to the Python parser
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
        lib.asa_packer_new.restype = ctypes.c_void_p
        lib.asa_packer_free.argtypes = [ctypes.c_void_p]
        lib.asa_packer_add_acl.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.asa_packer_add_binding.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.asa_packer_add_binding_out.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.asa_packer_parsed.argtypes = [ctypes.c_void_p]
        lib.asa_packer_parsed.restype = ctypes.c_int64
        lib.asa_packer_skipped.argtypes = [ctypes.c_void_p]
        lib.asa_packer_skipped.restype = ctypes.c_int64
        lib.asa_packer_set_counts.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        # buf params are c_void_p (not c_char_p) so both immutable bytes
        # and zero-copy views of a reusable bytearray can be passed
        lib.asa_pack_chunk.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.asa_pack_chunk.restype = ctypes.c_int64
        lib.asa_pack_chunk_mt.argtypes = lib.asa_pack_chunk.argtypes + [ctypes.c_int]
        lib.asa_pack_chunk_mt.restype = ctypes.c_int64
        # dual-family parse (v6-capable rulesets): v4 plane + TUPLE6 plane
        lib.asa_pack_chunk2.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
        ]
        lib.asa_pack_chunk2.restype = ctypes.c_int64
        lib.asa_count_lines.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.asa_count_lines.restype = ctypes.c_int64
        lib.asa_count_nl.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.asa_count_nl.restype = ctypes.c_int64
        # flow coalescing (ISSUE 5): open-addressing batch compaction
        lib.asa_coalesce.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
        ]
        lib.asa_coalesce.restype = ctypes.c_int64
        # SIMD tokenizer dispatch (ISSUE 11): runtime probe + A/B switch
        lib.asa_simd_kind.argtypes = []
        lib.asa_simd_kind.restype = ctypes.c_int
        lib.asa_simd_set.argtypes = [ctypes.c_int]


def available() -> bool:
    """True if the native parser library is loadable (building if needed)."""
    return _load() is not None


#: asa_simd_kind() codes -> human-readable ISA names.
_SIMD_KINDS = {0: "scalar", 1: "avx2", 2: "neon"}


def simd_kind() -> str:
    """Active tokenizer dispatch: ``"avx2"``/``"neon"``/``"scalar"``.

    ``"scalar"`` means the CPU lacks both ISAs, the library is not
    loadable, or ``RA_SIMD=off`` (the A/B override) disabled dispatch.
    """
    lib = _load()
    if lib is None:
        return "scalar"
    return _SIMD_KINDS.get(int(lib.asa_simd_kind()), "scalar")


def simd_active() -> bool:
    """True when a vectorized scan implementation is dispatched."""
    return simd_kind() != "scalar"


def set_simd(on: bool) -> str:
    """Force the tokenizer dispatch on/off at runtime; returns the
    resulting :func:`simd_kind`.

    The in-process twin of the ``RA_SIMD=off`` env override: the
    identity sweep and the feedscale bench flip this to compare scalar
    and SIMD parses of the same bytes in one process.  ``set_simd(True)``
    on a CPU without AVX2/NEON is a no-op (stays ``"scalar"``).
    """
    lib = _load()
    if lib is not None:
        lib.asa_simd_set(1 if on else 0)
    return simd_kind()


def native_coalesce(
    mat: np.ndarray, want_first: bool = False
) -> tuple[np.ndarray, np.ndarray | None] | None:
    """Native batch compaction, or None when the library is unavailable.

    ``mat`` is a C-contiguous ``[rows, B]`` uint32 plane whose LAST row
    is the weight/valid plane (see ``pack.coalesce_cols``, which owns the
    numpy fallback and the output contract — first-occurrence order,
    summed weights).  The hash pass releases the GIL (ctypes), so under
    the pipelined ingest producer it overlaps the device step.
    """
    lib = _load()
    if lib is None:
        return None
    rows, b = mat.shape
    if not mat.flags.c_contiguous:
        mat = np.ascontiguousarray(mat)
    scratch = np.empty((rows, b), dtype=np.uint32)
    first = np.empty(b, dtype=np.int64) if want_first else None
    u = int(
        lib.asa_coalesce(
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            rows,
            b,
            scratch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            first.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
            if first is not None
            else None,
        )
    )
    out = np.ascontiguousarray(scratch[:, :u])
    return out, (first[:u].copy() if first is not None else None)


class NativePacker:
    """Raw syslog bytes -> column-major [TUPLE_COLS, B] uint32 batches.

    Mirrors ``LinePacker`` exactly: the (firewall, acl)->gid and
    (firewall, iface)->gid resolution tables (both in- and out-direction)
    come from the same PackedRuleset, unresolvable or unparseable lines
    count as skipped, and valid tuples are packed densely from row 0.
    A connection line whose ingress interface has an ``in`` ACL and whose
    egress interface has an ``out`` ACL emits two rows; ``parsed`` counts
    evaluations, ``skipped`` counts lines that produced none.
    """

    def __init__(self, packed: PackedRuleset):
        from ..errors import NativeParserUnavailable

        lib = _load()
        if lib is None:
            raise NativeParserUnavailable(
                "native parser unavailable (no C++ toolchain to build "
                "ruleset_analysis_tpu/native/_asaparse.so?)"
            )
        self._lib = lib
        self._h = ctypes.c_void_p(lib.asa_packer_new())
        for (fw, acl), gid in packed.acl_gid.items():
            lib.asa_packer_add_acl(self._h, fw.encode(), acl.encode(), gid)
        for (fw, iface), gid in packed.bindings.items():
            lib.asa_packer_add_binding(self._h, fw.encode(), iface.encode(), gid)
        for (fw, iface), gid in packed.bindings_out.items():
            lib.asa_packer_add_binding_out(self._h, fw.encode(), iface.encode(), gid)
        #: with out-bindings a connection line can emit two rows; sizes
        #: the default pack_lines capacity like LinePacker.pack_parsed
        self._rows_per_line = 2 if packed.bindings_out else 1
        #: v6-capable ruleset: parse through the dual-family native entry
        #: and stage v6 rows for the driver's take_v6 side channel
        self._has_v6 = packed.has_v6
        self._staged6: list[np.ndarray] = []

    def take_v6(self):
        """Drain staged v6 rows as ONE ``[n, TUPLE6_COLS]`` uint32 array.

        Only meaningful for v6-capable rulesets; the stream driver pulls
        this after every batch, exactly as with the Python text source.
        Returned whole (not per-row objects) so consumers slice/transpose
        vectorized — per-row Python views would negate the native parse
        speed on v6-heavy corpora.  Empty list when nothing staged.
        """
        staged = self._staged6
        self._staged6 = []
        if not staged:
            return []
        if len(staged) == 1:
            return staged[0]
        return np.concatenate(staged)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.asa_packer_free(h)
            self._h = None

    @property
    def parsed(self) -> int:
        return int(self._lib.asa_packer_parsed(self._h))

    @property
    def skipped(self) -> int:
        return int(self._lib.asa_packer_skipped(self._h))

    def set_counts(self, parsed: int, skipped: int) -> None:
        """Restore cumulative counters (checkpoint resume)."""
        self._lib.asa_packer_set_counts(self._h, parsed, skipped)

    def pack_chunk(
        self,
        data: bytes | bytearray | memoryview,
        batch_size: int,
        *,
        final: bool,
        max_lines: int | None = None,
        n_threads: int | None = None,
        length: int | None = None,
        out: np.ndarray | None = None,
    ) -> tuple[np.ndarray, int, int]:
        """Parse up to ``max_lines`` (default batch_size) lines from data.

        Returns (batch [TUPLE_COLS, batch_size] uint32, lines_consumed,
        bytes_consumed).  With ``final=False`` a trailing fragment without
        a newline is left unconsumed — feed it back with the next block.
        ``n_threads`` (default :func:`default_parse_threads`) splits the
        parse across native workers; output is bit-identical for any
        thread count.  ``length`` limits the parse to ``data[:length]``
        (zero-copy prefix of a reusable buffer).  ``out`` supplies a
        preallocated ``[TUPLE_COLS, batch_size]`` uint32 C-contiguous
        destination (e.g. a shared-memory view) instead of a fresh array.
        """
        n = len(data) if length is None else length
        arg = _as_buffer(data)
        if out is None:
            out = np.empty((TUPLE_COLS, batch_size), dtype=np.uint32)
        else:
            if out.shape != (TUPLE_COLS, batch_size) or out.dtype != np.uint32:
                raise ValueError(
                    f"out must be [TUPLE_COLS, {batch_size}] uint32, got "
                    f"{out.shape} {out.dtype}"
                )
            if not out.flags.c_contiguous:
                raise ValueError("out must be C-contiguous")
        n_lines = ctypes.c_int64(0)
        n_valid = ctypes.c_int64(0)
        ml = max_lines if max_lines is not None else batch_size
        if self._has_v6:
            # dual-family entry: the v6 plane is sized 2*max_lines so v6
            # rows never close a batch, mirroring the Python text
            # source's side buffer; parses across n_threads workers with
            # bit-identical output (same slab/compaction structure as
            # the v4 MT path)
            cap6 = 2 * ml
            out6 = np.empty((TUPLE6_COLS, cap6), dtype=np.uint32)
            n_valid6 = ctypes.c_int64(0)
            used = self._lib.asa_pack_chunk2(
                self._h,
                arg,
                n,
                1 if final else 0,
                ml,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                batch_size,
                out6.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                cap6,
                ctypes.byref(n_lines),
                ctypes.byref(n_valid),
                ctypes.byref(n_valid6),
                n_threads if n_threads is not None else default_parse_threads(),
            )
            del arg
            if int(n_valid6.value):
                self._staged6.append(
                    np.ascontiguousarray(out6[:, : int(n_valid6.value)].T)
                )
            return out, int(n_lines.value), int(used)
        used = self._lib.asa_pack_chunk_mt(
            self._h,
            arg,
            n,
            1 if final else 0,
            ml,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            batch_size,
            ctypes.byref(n_lines),
            ctypes.byref(n_valid),
            n_threads if n_threads is not None else default_parse_threads(),
        )
        del arg  # release any buffer export before the caller resizes
        return out, int(n_lines.value), int(used)

    def _pack_lines_v4(self, lines: list[str], batch_size: int | None) -> np.ndarray:
        """The shared v4-plane pack (v6 rows, if any, land in _staged6)."""
        data = "".join(ln if ln.endswith("\n") else ln + "\n" for ln in lines).encode()
        b = batch_size if batch_size is not None else self._rows_per_line * len(lines)
        out, _, _ = self.pack_chunk(data, b, final=True, max_lines=len(lines))
        return np.ascontiguousarray(out.T)

    def pack_lines(self, lines: list[str], batch_size: int | None = None) -> np.ndarray:
        """LinePacker-compatible helper (row-major [B, TUPLE_COLS]).

        Returns the v4 plane only; v6 evaluations the parse produced stay
        staged for :meth:`take_v6`, exactly like the chunk API and the
        streaming drivers (ISSUE 11 closed the last v6-refusing tier, so
        this call follows the same side-channel contract instead of the
        old loud v4-only refusal).  Callers that never drain
        :meth:`take_v6` on a unified corpus would accumulate staged rows
        — the historical reason for the refusal (ADVICE r5 #2) — so
        prefer :meth:`pack_lines2` when v6 traffic is possible.
        """
        return self._pack_lines_v4(lines, batch_size)

    def pack_lines2(
        self, lines: list[str], batch_size: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """LinePacker.pack_lines2-compatible helper (padded row-major pair)."""
        b4 = self._pack_lines_v4(lines, batch_size)
        b = b4.shape[0]
        rows6 = self.take_v6()
        out6 = np.zeros((b if self._has_v6 else 0, TUPLE6_COLS), dtype=np.uint32)
        for i, r in enumerate(rows6):
            out6[i] = r
        return b4, out6


class _ChainedReader:
    """Several files as one byte stream, with line-boundary parity.

    A file whose last line is unterminated still contributes that line as
    a line of its own on the text path (``yield from f``); to keep the
    byte stream identical, a ``\\n`` is synthesized at any file boundary
    where the previous file did not end with one.
    """

    def __init__(self, paths: list[str]):
        self._paths = list(paths)
        self._i = 0
        self._f = None
        self._last = b"\n"

    def read(self, n: int) -> bytes:
        while True:
            if self._f is None:
                if self._i >= len(self._paths):
                    return b""
                self._f = open(self._paths[self._i], "rb")
                self._i += 1
            block = self._f.read(n)
            if block:
                self._last = block[-1:]
                return block
            self._f.close()
            self._f = None
            if self._last != b"\n":
                self._last = b"\n"
                return b"\n"

    def readinto(self, view: memoryview) -> int:
        """Fill ``view`` from the stream; 0 only at end of all files."""
        while True:
            if self._f is None:
                if self._i >= len(self._paths):
                    return 0
                self._f = open(self._paths[self._i], "rb")
                self._i += 1
            n = self._f.readinto(view)
            if n:
                self._last = bytes(view[n - 1 : n])
                return n
            self._f.close()
            self._f = None
            if self._last != b"\n":
                self._last = b"\n"
                view[0:1] = b"\n"
                return 1

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def batches_from_files(
    paths: list[str],
    packer: NativePacker,
    batch_size: int,
    *,
    skip_lines: int = 0,
    read_block: int = READ_BLOCK,
) -> Iterator[tuple[np.ndarray, int]]:
    """Yield (batch [TUPLE_COLS, batch_size], raw_line_count) over files.

    The files are chained into one stream, so batch boundaries fall
    exactly where the pure-Python text path puts them — per-chunk outputs
    (top-K candidates) match, not just the merged registers.
    ``skip_lines`` raw lines are skipped first without parsing
    (checkpoint resume); raises if the input has fewer lines than that.
    """
    lib = packer._lib
    reader = _ChainedReader(paths)
    try:
        # Buffer management: one reusable bytearray filled with readinto —
        # no per-block copies, no join.  (A naive ``rem += block`` chain
        # re-copies the accumulated buffer per read — ~1.7 GB of memcpy
        # per 1M-line batch — and was measured to cost 4x end-to-end
        # throughput.)  After each batch the unconsumed tail (at most
        # ~read_block bytes) moves to the front.
        buf = bytearray(2 * read_block)
        filled = 0  # bytes of buf holding live data
        nl = 0  # newlines within buf[:filled]
        eof = False

        def count_nl(start: int, end_: int) -> int:
            if end_ <= start:
                return 0
            arr = (ctypes.c_char * (end_ - start)).from_buffer(buf, start)
            try:
                return int(lib.asa_count_nl(arr, end_ - start))
            finally:
                del arr

        def fill() -> None:
            nonlocal filled, nl, eof
            if eof:
                return
            if len(buf) - filled < read_block:
                buf.extend(bytes(len(buf)))  # grow geometrically
            with memoryview(buf) as mv:
                n = reader.readinto(mv[filled : filled + read_block])
            if n == 0:
                eof = True
            else:
                nl += count_nl(filled, filled + n)
                filled += n

        def consume(used: int) -> None:
            """Drop buf[:used]; move the tail to the front."""
            nonlocal filled, nl
            if used == 0:
                return
            tail = filled - used
            buf[0:tail] = buf[used:filled]
            filled = tail
            nl = count_nl(0, filled)

        # ---- resume fast-skip
        to_skip = skip_lines
        while to_skip > 0:
            if filled == 0 and not eof:
                fill()
            if filled == 0 and eof:
                from ..errors import ResumeInputMismatch

                raise ResumeInputMismatch(
                    f"snapshot consumed {skip_lines} lines but the input has "
                    f"only {skip_lines - to_skip}; wrong or truncated log input"
                )
            bytes_used = ctypes.c_int64(0)
            arg = _as_buffer(buf)
            skipped = lib.asa_count_lines(
                arg, filled, 1 if eof else 0, to_skip, ctypes.byref(bytes_used)
            )
            del arg
            to_skip -= int(skipped)
            consume(int(bytes_used.value))
            if to_skip > 0 and int(skipped) == 0:
                # newline-free fragment: grow the buffer to make progress
                fill()
        # ---- stream batches
        # Buffer until batch_size COMPLETE lines are held (not merely
        # read_block bytes), then close each batch line-atomically: at
        # most batch_size raw lines AND at most batch_size tuple rows —
        # with out-direction bindings a dual-evaluation line can close a
        # batch early, exactly as _TextSource does, so chunk boundaries —
        # and therefore per-chunk top-K candidates and resume offsets —
        # land exactly where the pure-Python text path puts them.
        while True:
            while not eof and nl < batch_size:
                fill()
            if filled == 0 and eof:
                return
            batch, n_lines, used = packer.pack_chunk(
                buf, batch_size, final=eof, length=filled
            )
            consume(used)
            if n_lines == 0:
                if eof:
                    return
                # no complete line yet (line longer than the buffered
                # bytes): force another read so we always make progress
                fill()
                continue
            yield batch, n_lines
    finally:
        reader.close()


def batches_from_file(
    path: str,
    packer: NativePacker,
    batch_size: int,
    *,
    skip_lines: int = 0,
    read_block: int = READ_BLOCK,
) -> Iterator[tuple[np.ndarray, int]]:
    """Single-file convenience wrapper over :func:`batches_from_files`."""
    return batches_from_files(
        [path], packer, batch_size, skip_lines=skip_lines, read_block=read_block
    )


def count_lines_in_file(path: str, read_block: int = READ_BLOCK) -> int:
    """Raw line count (trailing unterminated fragment counts as a line)."""
    n = 0
    tail_fragment = False
    with open(path, "rb") as f:
        while True:
            block = f.read(read_block)
            if not block:
                break
            n += block.count(b"\n")
            tail_fragment = not block.endswith(b"\n")
    return n + (1 if tail_fragment else 0)
