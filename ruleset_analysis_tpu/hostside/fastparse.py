"""Native fast path for the host parse: ctypes binding to _asaparse.so.

The reference's mapper spends its host CPU in regex parsing (SURVEY.md
§4.3); at TPU-scale feed rates that parse is the end-to-end bottleneck
(SURVEY.md §8.2).  This module loads the C++ parser/packer from
``ruleset_analysis_tpu/native/`` (building it with make/g++ on first use)
and exposes:

- :class:`NativePacker` — drop-in producer of the same column-major
  ``[TUPLE_COLS, B]`` uint32 batches as ``LinePacker.pack_lines(...).T``,
  but straight from raw bytes;
- :func:`batches_from_file` — stream a syslog file (or byte stream) as
  device-ready batches of ``batch_size`` raw lines each.

If no C++ toolchain is available the import still succeeds and
``available()`` returns False; callers fall back to the pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from collections.abc import Iterator

import numpy as np

from .pack import PackedRuleset, TUPLE_COLS

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "_asaparse.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

#: Bytes per read when streaming a file through the native parser.
READ_BLOCK = 8 << 20


def _build() -> bool:
    try:
        r = subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            capture_output=True,
            text=True,
            timeout=120,
        )
        return r.returncode == 0 and os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.asa_packer_new.restype = ctypes.c_void_p
        lib.asa_packer_free.argtypes = [ctypes.c_void_p]
        lib.asa_packer_add_acl.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.asa_packer_add_binding.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.asa_packer_parsed.argtypes = [ctypes.c_void_p]
        lib.asa_packer_parsed.restype = ctypes.c_int64
        lib.asa_packer_skipped.argtypes = [ctypes.c_void_p]
        lib.asa_packer_skipped.restype = ctypes.c_int64
        lib.asa_packer_set_counts.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        lib.asa_pack_chunk.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.asa_pack_chunk.restype = ctypes.c_int64
        lib.asa_count_lines.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.asa_count_lines.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native parser library is loadable (building if needed)."""
    return _load() is not None


class NativePacker:
    """Raw syslog bytes -> column-major [TUPLE_COLS, B] uint32 batches.

    Mirrors ``LinePacker`` exactly: the (firewall, acl)->gid and
    (firewall, iface)->gid resolution tables come from the same
    PackedRuleset, unresolvable or unparseable lines count as skipped,
    and valid tuples are packed densely from row 0.
    """

    def __init__(self, packed: PackedRuleset):
        from ..errors import NativeParserUnavailable

        lib = _load()
        if lib is None:
            raise NativeParserUnavailable(
                "native parser unavailable (no C++ toolchain to build "
                "ruleset_analysis_tpu/native/_asaparse.so?)"
            )
        self._lib = lib
        self._h = ctypes.c_void_p(lib.asa_packer_new())
        for (fw, acl), gid in packed.acl_gid.items():
            lib.asa_packer_add_acl(self._h, fw.encode(), acl.encode(), gid)
        for (fw, iface), gid in packed.bindings.items():
            lib.asa_packer_add_binding(self._h, fw.encode(), iface.encode(), gid)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.asa_packer_free(h)
            self._h = None

    @property
    def parsed(self) -> int:
        return int(self._lib.asa_packer_parsed(self._h))

    @property
    def skipped(self) -> int:
        return int(self._lib.asa_packer_skipped(self._h))

    def set_counts(self, parsed: int, skipped: int) -> None:
        """Restore cumulative counters (checkpoint resume)."""
        self._lib.asa_packer_set_counts(self._h, parsed, skipped)

    def pack_chunk(
        self,
        data: bytes | bytearray | memoryview,
        batch_size: int,
        *,
        final: bool,
        max_lines: int | None = None,
    ) -> tuple[np.ndarray, int, int]:
        """Parse up to ``max_lines`` (default batch_size) lines from data.

        Returns (batch [TUPLE_COLS, batch_size] uint32, lines_consumed,
        bytes_consumed).  With ``final=False`` a trailing fragment without
        a newline is left unconsumed — feed it back with the next block.
        """
        buf = bytes(data) if not isinstance(data, bytes) else data
        out = np.zeros((TUPLE_COLS, batch_size), dtype=np.uint32)
        n_lines = ctypes.c_int64(0)
        n_valid = ctypes.c_int64(0)
        used = self._lib.asa_pack_chunk(
            self._h,
            buf,
            len(buf),
            1 if final else 0,
            max_lines if max_lines is not None else batch_size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            batch_size,
            ctypes.byref(n_lines),
            ctypes.byref(n_valid),
        )
        return out, int(n_lines.value), int(used)

    def pack_lines(self, lines: list[str], batch_size: int | None = None) -> np.ndarray:
        """LinePacker-compatible helper (row-major [B, TUPLE_COLS])."""
        data = "".join(ln if ln.endswith("\n") else ln + "\n" for ln in lines).encode()
        b = batch_size or len(lines)
        out, _, _ = self.pack_chunk(data, b, final=True, max_lines=len(lines))
        return np.ascontiguousarray(out.T)


class _ChainedReader:
    """Several files as one byte stream, with line-boundary parity.

    A file whose last line is unterminated still contributes that line as
    a line of its own on the text path (``yield from f``); to keep the
    byte stream identical, a ``\\n`` is synthesized at any file boundary
    where the previous file did not end with one.
    """

    def __init__(self, paths: list[str]):
        self._paths = list(paths)
        self._i = 0
        self._f = None
        self._last = b"\n"

    def read(self, n: int) -> bytes:
        while True:
            if self._f is None:
                if self._i >= len(self._paths):
                    return b""
                self._f = open(self._paths[self._i], "rb")
                self._i += 1
            block = self._f.read(n)
            if block:
                self._last = block[-1:]
                return block
            self._f.close()
            self._f = None
            if self._last != b"\n":
                self._last = b"\n"
                return b"\n"

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def batches_from_files(
    paths: list[str],
    packer: NativePacker,
    batch_size: int,
    *,
    skip_lines: int = 0,
    read_block: int = READ_BLOCK,
) -> Iterator[tuple[np.ndarray, int]]:
    """Yield (batch [TUPLE_COLS, batch_size], raw_line_count) over files.

    The files are chained into one stream, so batch boundaries fall
    exactly where the pure-Python text path puts them — per-chunk outputs
    (top-K candidates) match, not just the merged registers.
    ``skip_lines`` raw lines are skipped first without parsing
    (checkpoint resume); raises if the input has fewer lines than that.
    """
    lib = packer._lib
    reader = _ChainedReader(paths)
    try:
        rem = b""
        eof = False

        def fill() -> None:
            nonlocal rem, eof
            if eof:
                return
            block = reader.read(read_block)
            if not block:
                eof = True
            else:
                rem += block

        # ---- resume fast-skip
        to_skip = skip_lines
        while to_skip > 0:
            if not rem and not eof:
                fill()
            if not rem and eof:
                from ..errors import ResumeInputMismatch

                raise ResumeInputMismatch(
                    f"snapshot consumed {skip_lines} lines but the input has "
                    f"only {skip_lines - to_skip}; wrong or truncated log input"
                )
            bytes_used = ctypes.c_int64(0)
            skipped = lib.asa_count_lines(
                rem, len(rem), 1 if eof else 0, to_skip, ctypes.byref(bytes_used)
            )
            to_skip -= int(skipped)
            rem = rem[int(bytes_used.value):]
            if to_skip > 0 and int(skipped) == 0:
                # newline-free fragment: grow the buffer to make progress
                fill()
        # ---- stream batches
        # Buffer until batch_size COMPLETE lines are in rem (not merely
        # read_block bytes): every mid-stream batch must hold exactly
        # batch_size raw lines so chunk boundaries — and therefore
        # per-chunk top-K candidates and resume offsets — land exactly
        # where the pure-Python text path puts them.
        nl = rem.count(b"\n")
        while True:
            while not eof and nl < batch_size:
                n0 = len(rem)
                fill()
                nl += rem.count(b"\n", n0)
            if not rem and eof:
                return
            batch, n_lines, used = packer.pack_chunk(rem, batch_size, final=eof)
            rem = rem[used:]
            nl = rem.count(b"\n")
            if n_lines == 0:
                if eof:
                    return
                # no complete line yet (line longer than the buffered
                # bytes): force another read so we always make progress
                n0 = len(rem)
                fill()
                nl += rem.count(b"\n", n0)
                continue
            yield batch, n_lines
    finally:
        reader.close()


def batches_from_file(
    path: str,
    packer: NativePacker,
    batch_size: int,
    *,
    skip_lines: int = 0,
    read_block: int = READ_BLOCK,
) -> Iterator[tuple[np.ndarray, int]]:
    """Single-file convenience wrapper over :func:`batches_from_files`."""
    return batches_from_files(
        [path], packer, batch_size, skip_lines=skip_lines, read_block=read_block
    )


def count_lines_in_file(path: str, read_block: int = READ_BLOCK) -> int:
    """Raw line count (trailing unterminated fragment counts as a line)."""
    n = 0
    tail_fragment = False
    with open(path, "rb") as f:
        while True:
            block = f.read(read_block)
            if not block:
                break
            n += block.count(b"\n")
            tail_fragment = not block.endswith(b"\n")
    return n + (1 if tail_fragment else 0)
