"""Multi-process host feed: parallel parse workers over file shards.

The reborn Hadoop input split (SURVEY.md §2 L2), one level above the
native parser's in-process threads: N worker PROCESSES each run a
:class:`fastparse.NativePacker` over byte ranges of the input files and
pack straight into shared-memory slots; the coordinator hands the device
driver batches in input order.  On a multi-core host this scales the
parse stage — the e2e bottleneck once transfers are fast — nearly
linearly with workers, without the GIL or per-batch pickling.

Layout decisions:

- The coordinator pre-chops files into batch descriptors of exactly
  ``batch_size`` raw lines using the native newline scanner — byte
  ranges only, no parsing.  Workers read their range straight from the
  file (page cache makes this nearly free) so no input bytes cross a
  queue; only tiny descriptors and completions do.
- Output slots hold ``rows_cap = 2 x batch_size`` rows when any
  out-direction binding exists (a connection line can emit two
  evaluations), else ``batch_size``.  Since a descriptor never holds
  more than ``batch_size`` lines, every line always fits and batches
  stay aligned to the precomputed raw-line boundaries.
- parsed/skipped counters ride each completion and fold into the
  feeder's totals when its batch is YIELDED, so checkpoint snapshots
  (taken at chunk boundaries) stay coherent with consumed input.

Requires the native parser; the pure-Python path has no multi-process
tier (it is not the deployment path).
"""

from __future__ import annotations

import multiprocessing
import pickle
import time
from multiprocessing import shared_memory

import numpy as np

from . import fastparse
from ..errors import FeedWorkerError, StallError
from ..runtime import faults, obs
from .pack import PackedRuleset, TUPLE_COLS, TUPLE6_COLS

#: Coordinator read granularity while scanning for batch boundaries.
SCAN_BLOCK = 8 << 20


def default_feed_workers() -> int:
    return fastparse.host_workers("RA_FEED_WORKERS", 16)


def _scan_batches(paths: list[str], batch_size: int, skip_lines: int):
    """Yield (path_idx, offset, nbytes, n_lines) descriptors.

    Each descriptor covers exactly ``batch_size`` raw lines (the final
    one per file may be short; descriptors never span files).  The first
    ``skip_lines`` lines are consumed without emitting (resume).
    """
    lib = fastparse._load()
    if lib is None:
        from ..errors import NativeParserUnavailable

        raise NativeParserUnavailable("feeder requires the native parser")
    import ctypes

    to_skip = skip_lines
    for path_i, path in enumerate(paths):
        with open(path, "rb") as f:
            buf = b""
            base = 0  # file offset of buf[0]
            pos = 0  # consumed bytes within buf
            eof = False
            pend_lines = 0  # lines in the current (incomplete) descriptor
            pend_start = 0  # absolute file offset where it starts

            def refill():
                nonlocal buf, base, pos, eof
                block = f.read(SCAN_BLOCK)
                if not block:
                    eof = True
                    return
                buf = buf[pos:] + block
                base += pos
                pos = 0

            while True:
                avail = len(buf) - pos
                if avail == 0:
                    if eof:
                        break
                    refill()
                    continue
                want = to_skip if to_skip > 0 else batch_size - pend_lines
                # zero-copy pointer into buf at pos (buf outlives the call)
                arr = np.frombuffer(buf, dtype=np.uint8)
                used = ctypes.c_int64(0)
                got = int(
                    lib.asa_count_lines(
                        ctypes.c_void_p(arr.ctypes.data + pos), avail,
                        1 if eof else 0, want, ctypes.byref(used),
                    )
                )
                if got == 0:
                    if eof:
                        break
                    refill()  # a line longer than the buffered bytes
                    continue
                if to_skip > 0:
                    to_skip -= got
                    pos += int(used.value)
                    continue
                if pend_lines == 0:
                    pend_start = base + pos
                pend_lines += got
                pos += int(used.value)
                if pend_lines == batch_size:
                    yield (path_i, pend_start, base + pos - pend_start, pend_lines)
                    pend_lines = 0
            if pend_lines:
                yield (path_i, pend_start, base + pos - pend_start, pend_lines)
    if to_skip > 0:
        from ..errors import ResumeInputMismatch

        raise ResumeInputMismatch(
            f"snapshot consumed {skip_lines} lines but the input ran short "
            f"by {to_skip}"
        )


def _worker(packed_blob, paths, rows_cap, rows6_cap, shm_name, task_q, done_q):
    # span shards arm lazily from the inherited RA_TRACE_DIR (the same
    # env channel the fault plan rides); the label makes this process's
    # track readable in the merged timeline
    obs.note_role("feeder-worker")
    packed = pickle.loads(packed_blob)
    packer = fastparse.NativePacker(packed)
    shm = shared_memory.SharedMemory(name=shm_name)
    slot_words = TUPLE_COLS * rows_cap + TUPLE6_COLS * rows6_cap
    files = {}
    try:
        while True:
            task = task_q.get()
            if task is None:
                return
            t0_span = time.perf_counter()
            # fault sites (plan arrives via the inherited RA_FAULT_PLAN
            # env): abrupt death — the OOM-kill the coordinator's
            # liveness probe must catch — and a wedge the coordinator's
            # stall watchdog must bound
            faults.fire("feeder.worker.crash")
            faults.fire("feeder.worker.stall")
            idx, slot, path_i, offset, nbytes, n_lines = task
            try:
                f = files.get(path_i)
                if f is None:
                    f = files[path_i] = open(paths[path_i], "rb")
                f.seek(offset)
                data = f.read(nbytes)
                out = np.ndarray(
                    (TUPLE_COLS, rows_cap), dtype=np.uint32, buffer=shm.buf,
                    offset=4 * slot * slot_words,
                )
                p0, s0 = packer.parsed, packer.skipped
                _, lines, _used = packer.pack_chunk(
                    data, rows_cap, final=True, max_lines=n_lines, n_threads=1,
                    out=out,
                )
                n6 = 0
                if rows6_cap:
                    # v6 rows the dual-family parse staged for this range
                    # ride the slot's second plane (input order preserved:
                    # the coordinator attributes them when idx yields)
                    rows6 = packer.take_v6()
                    n6 = len(rows6)
                    if n6:
                        plane6 = np.ndarray(
                            (TUPLE6_COLS, rows6_cap), dtype=np.uint32,
                            buffer=shm.buf,
                            offset=4 * (slot * slot_words + TUPLE_COLS * rows_cap),
                        )
                        plane6[:, :n6] = np.asarray(rows6, dtype=np.uint32).T
            except Exception as e:  # forward instead of dying silently
                done_q.put(("error", idx, f"{type(e).__name__}: {e}"))
                return
            obs.complete(
                "feeder.parse", t0_span, time.perf_counter(), cat="feeder",
                args={"batch": idx, "lines": lines},
            )
            done_q.put(
                (idx, slot, lines, packer.parsed - p0, packer.skipped - s0, n6)
            )
    finally:
        for f in files.values():
            f.close()
        shm.close()


class _FeedCounters:
    def __init__(self):
        self.parsed = 0
        self.skipped = 0


class _FeederBase:
    """Shared source-protocol state of the multi-worker feed tiers.

    Both tiers commit worker completions in input order: parsed/skipped
    deltas fold into ``.packer`` and v6 rows stage for ``take_v6`` only
    when their batch is YIELDED, so checkpoint snapshots stay coherent
    with consumed input no matter how far workers ran ahead.
    """

    def __init__(
        self,
        packed: PackedRuleset,
        paths: list[str],
        n_workers: int | None = None,
        stall_timeout: float | None = None,
    ):
        if not fastparse.available():
            from ..errors import NativeParserUnavailable

            raise NativeParserUnavailable("feeder requires the native parser")
        self.packed = packed
        self.paths = list(paths)
        self.n_workers = n_workers or default_feed_workers()
        #: watchdog bound: workers alive but completing nothing for this
        #: long is a wedge, escalated to a typed StallError abort
        self.stall_timeout = (
            stall_timeout if stall_timeout and stall_timeout > 0
            else faults.default_stall_timeout()
        )
        self.packer = _FeedCounters()
        self._resume_counts = (0, 0)
        self._v6chunks: list[np.ndarray] = []  # [n,13] arrays, input order
        #: digest -> 128-bit source for talker rendering (same contract
        #: as the other sources)
        self.v6_digests: dict[int, int] = {}

    def set_counts(self, parsed: int, skipped: int) -> None:
        self._resume_counts = (parsed, skipped)

    def take_v6(self):
        """Staged v6 rows as one [n, 13] array (or [] when none)."""
        chunks = self._v6chunks
        self._v6chunks = []
        if not chunks:
            return []
        if len(chunks) == 1:
            return chunks[0]
        return np.concatenate(chunks)

    def _stage_v6(self, rows6: np.ndarray) -> None:
        """Commit one batch's v6 rows + talker digests, in input order."""
        from .pack import T6_SRC, V6_DIGEST_CAP, fold_src32_host, limbs_u128

        dig = self.v6_digests
        for r in rows6:
            if len(dig) >= V6_DIGEST_CAP:
                break
            src = limbs_u128(*r[T6_SRC:T6_SRC + 4])
            dig.setdefault(fold_src32_host(src), src)
        self._v6chunks.append(rows6)


class ParallelFeeder(_FeederBase):
    """Stream-source over files backed by N parse worker processes.

    Drop-in for the stream driver's source protocol: ``.packer`` exposes
    parsed/skipped counters and ``.batches(skip_lines, batch_size)``
    yields ``([TUPLE_COLS, rows_cap] uint32, raw_line_count)`` in input
    order.  ``rows_cap`` is fixed per run (2x batch_size with
    out-bindings), so one compiled device program serves every chunk.
    """

    def batches(self, skip_lines: int, batch_size: int):
        self.packer.parsed, self.packer.skipped = self._resume_counts
        rows_cap = (2 if self.packed.bindings_out else 1) * batch_size
        # v6 plane: any line of a batch can be a dual-evaluation v6 line
        rows6_cap = 2 * batch_size if self.packed.has_v6 else 0
        n_slots = 2 * self.n_workers + 2
        slot_bytes = 4 * (TUPLE_COLS * rows_cap + TUPLE6_COLS * rows6_cap)
        shm = shared_memory.SharedMemory(create=True, size=n_slots * slot_bytes)
        # spawn, not fork: the driver process runs JAX's thread pools, and
        # forking a multi-threaded process can deadlock the child.  The
        # workers import only numpy + the native parser, so spawn is cheap.
        ctx = multiprocessing.get_context("spawn")
        task_q = ctx.Queue()
        done_q = ctx.Queue()
        blob = pickle.dumps(self.packed)
        workers = [
            ctx.Process(
                target=_worker,
                args=(blob, self.paths, rows_cap, rows6_cap, shm.name,
                      task_q, done_q),
                daemon=True,
            )
            for _ in range(self.n_workers)
        ]
        for w in workers:
            w.start()
        self._workers = workers  # exposed for fault-injection tests
        try:
            free_slots = list(range(n_slots))
            ready: dict[int, tuple] = {}  # idx -> completion
            next_submit = 0
            next_yield = 0
            desc_it = _scan_batches(self.paths, batch_size, skip_lines)
            descs_done = False

            def submit_until_full():
                nonlocal next_submit, descs_done
                while free_slots and not descs_done:
                    d = next(desc_it, None)
                    if d is None:
                        descs_done = True
                        break
                    slot = free_slots.pop()
                    task_q.put((next_submit, slot, *d))
                    next_submit += 1

            import queue as _queue

            def _occupancy() -> dict:
                # pool gauges for the metrics snapshotter: how many
                # descriptors are in flight vs workers still alive
                return {
                    "mode": "process",
                    "workers": len(workers),
                    "alive": sum(1 for w in workers if w.is_alive()),
                    "inflight": next_submit - next_yield,
                    "ready": len(ready),
                    "free_slots": len(free_slots),
                }

            obs.register_sampler("feeder", _occupancy)
            submit_until_full()
            stall_deadline = time.monotonic() + self.stall_timeout
            while next_yield < next_submit:
                while next_yield not in ready:
                    # timeout + liveness: a worker killed by the OS (OOM)
                    # can't forward its error, and waiting forever on its
                    # completion would hang the whole analysis silently
                    try:
                        msg = done_q.get(timeout=5.0)
                    except _queue.Empty:
                        dead = [w.pid for w in workers if not w.is_alive()]
                        if dead:
                            raise FeedWorkerError(
                                f"feeder worker(s) {dead} died without "
                                "reporting (killed by the OS?)"
                            )
                        if time.monotonic() > stall_deadline:
                            # alive but completing nothing: a wedged
                            # worker (stuck I/O, injected stall) must be
                            # a bounded typed abort, not a silent hang
                            raise StallError(
                                f"feeder workers made no progress in "
                                f"{self.stall_timeout:.0f}s "
                                f"({len(workers)} alive); raise "
                                "--stall-timeout if the input is "
                                "legitimately this slow"
                            )
                        continue
                    # progress: any completion resets the stall window
                    stall_deadline = time.monotonic() + self.stall_timeout
                    if msg[0] == "error":
                        raise FeedWorkerError(
                            f"feeder worker failed on batch {msg[1]}: {msg[2]}"
                        )
                    idx, slot, lines, dp, ds, n6 = msg
                    ready[idx] = (slot, lines, dp, ds, n6)
                slot, lines, dp, ds, n6 = ready.pop(next_yield)
                slot_words = TUPLE_COLS * rows_cap + TUPLE6_COLS * rows6_cap
                out = np.ndarray(
                    (TUPLE_COLS, rows_cap), dtype=np.uint32, buffer=shm.buf,
                    offset=4 * slot * slot_words,
                ).copy()  # the slot is reused; the driver may hold the batch
                if n6:
                    plane6 = np.ndarray(
                        (TUPLE6_COLS, rows6_cap), dtype=np.uint32,
                        buffer=shm.buf,
                        offset=4 * (slot * slot_words + TUPLE_COLS * rows_cap),
                    )
                    self._stage_v6(np.ascontiguousarray(plane6[:, :n6].T))
                free_slots.append(slot)
                next_yield += 1
                self.packer.parsed += dp
                self.packer.skipped += ds
                submit_until_full()
                yield out, lines
        finally:
            obs.unregister_sampler("feeder")
            # Bounded teardown, also on a consumer-side exception: poison
            # pills, ONE shared join budget (a wedged worker must not
            # serialize N x 10s), terminate + reap stragglers, and close
            # the queues so their feeder threads don't outlive the run.
            for _ in workers:
                task_q.put(None)
            deadline = time.monotonic() + 10.0
            for w in workers:
                w.join(timeout=max(0.0, deadline - time.monotonic()))
            for w in workers:
                if w.is_alive():
                    w.terminate()
            for w in workers:
                w.join(timeout=5)
            for q in (task_q, done_q):
                q.cancel_join_thread()
                q.close()
            shm.close()
            shm.unlink()


class ThreadedFeeder(_FeederBase):
    """In-process threaded twin of :class:`ParallelFeeder`.

    Worker THREADS parse the same exact-raw-line byte-range descriptors
    the coordinator scans; the native parser releases the GIL for the
    parse itself, so threads scale across cores with no spawn cost, no
    pickling, and no shared-memory plumbing — the tier of choice when
    the driver process can spare cores (the prefetching ingest engine
    stacks on top, overlapping whichever tier feeds it with the device
    step).  Each thread builds ONE NativePacker lazily (the gid tables
    are per-thread, reused across its descriptors); completions commit
    strictly in input order with their parsed/skipped deltas and staged
    v6 rows, so batch boundaries — and the top-K caveat — are identical
    to the process tier over the same input.
    """

    def batches(self, skip_lines: int, batch_size: int):
        import concurrent.futures as cf
        import threading

        self.packer.parsed, self.packer.skipped = self._resume_counts
        rows_cap = (2 if self.packed.bindings_out else 1) * batch_size
        has_v6 = self.packed.has_v6
        tl = threading.local()
        # every handle any worker thread opens, for deterministic release
        # in the finally below (thread-local GC alone would hold fds open
        # past an early consumer exit — the same discipline _run_core's
        # close() applies to wire mmaps)
        files_lock = threading.Lock()
        opened: list = []

        stop_ev = threading.Event()  # releases injected stalls at teardown

        def work(desc):
            t0_span = time.perf_counter()
            # thread-tier twin of the process worker's fault sites (no
            # crash site: os._exit here would take the driver down)
            faults.fire("feeder.worker.stall", stop=stop_ev)
            path_i, offset, nbytes, n_lines = desc
            pk = getattr(tl, "packer", None)
            if pk is None:
                pk = tl.packer = fastparse.NativePacker(self.packed)
                tl.files = {}
            f = tl.files.get(path_i)
            if f is None:
                f = tl.files[path_i] = open(self.paths[path_i], "rb")
                with files_lock:
                    opened.append(f)
            f.seek(offset)
            data = f.read(nbytes)
            p0, s0 = pk.parsed, pk.skipped
            batch, lines, _used = pk.pack_chunk(
                data, rows_cap, final=True, max_lines=n_lines, n_threads=1
            )
            rows6 = pk.take_v6() if has_v6 else []
            obs.complete(
                "feeder.parse", t0_span, time.perf_counter(), cat="feeder",
                args={"lines": lines},
            )
            return batch, lines, pk.parsed - p0, pk.skipped - s0, rows6

        from collections import deque

        desc_it = _scan_batches(self.paths, batch_size, skip_lines)
        ex = cf.ThreadPoolExecutor(
            max_workers=self.n_workers, thread_name_prefix="ra-feed"
        )
        inflight: deque = deque()
        max_inflight = 2 * self.n_workers + 2
        stalled = False
        try:
            obs.register_sampler(
                "feeder",
                lambda: {
                    "mode": "thread",
                    "workers": self.n_workers,
                    "inflight": len(inflight),
                },
            )

            def fill() -> None:
                while len(inflight) < max_inflight:
                    d = next(desc_it, None)
                    if d is None:
                        return
                    inflight.append(ex.submit(work, d))

            fill()
            while inflight:
                fut = inflight.popleft()
                try:
                    # stall watchdog: a worker thread that wedges (stuck
                    # I/O, injected stall) bounds to a typed abort — the
                    # batches commit in submission order, so waiting on
                    # THIS future is exactly producer-to-consumer progress
                    batch, lines, dp, ds, rows6 = fut.result(
                        timeout=self.stall_timeout
                    )
                except cf.TimeoutError:
                    stalled = True
                    raise StallError(
                        f"feed worker made no progress in "
                        f"{self.stall_timeout:.0f}s; raise --stall-timeout "
                        "if the input is legitimately this slow"
                    ) from None
                except Exception as e:
                    raise FeedWorkerError(
                        f"feed worker failed: {type(e).__name__}: {e}"
                    ) from e
                self.packer.parsed += dp
                self.packer.skipped += ds
                if len(rows6):
                    self._stage_v6(np.asarray(rows6, dtype=np.uint32))
                fill()
                yield batch, lines
        finally:
            obs.unregister_sampler("feeder")
            # release injected stalls FIRST so the bounded shutdown below
            # cannot wedge on a thread parked in a fault site
            stop_ev.set()
            # wait: a worker mid-descriptor must finish before its file
            # handles close under it (each task is one bounded parse).
            # EXCEPT after a stall verdict: a thread wedged in an OS call
            # cannot be cancelled, and waiting on it would turn the typed
            # StallError into the very hang the watchdog exists to
            # prevent — abandon it (the process tier, which CAN terminate
            # its workers, is the tier of choice for hostile inputs)
            ex.shutdown(wait=not stalled, cancel_futures=True)
            with files_lock:
                for f in opened:
                    f.close()
