"""Multi-process host feed: parallel parse workers over file shards.

The reborn Hadoop input split (SURVEY.md §2 L2), one level above the
native parser's in-process threads: N worker PROCESSES each run a
:class:`fastparse.NativePacker` over byte ranges of the input files and
pack straight into shared-memory slots; the coordinator hands the device
driver batches in input order.  On a multi-core host this scales the
parse stage — the e2e bottleneck once transfers are fast — nearly
linearly with workers, without the GIL or per-batch pickling.

Layout decisions:

- The coordinator pre-chops files into batch descriptors of exactly
  ``batch_size`` raw lines using the native newline scanner — byte
  ranges only, no parsing.  Workers read their range straight from the
  file (page cache makes this nearly free) so no input bytes cross a
  queue; only tiny descriptors and completions do.
- Output slots hold ``rows_cap = 2 x batch_size`` rows when any
  out-direction binding exists (a connection line can emit two
  evaluations), else ``batch_size``.  Since a descriptor never holds
  more than ``batch_size`` lines, every line always fits and batches
  stay aligned to the precomputed raw-line boundaries.
- parsed/skipped counters ride each completion and fold into the
  feeder's totals when its batch is YIELDED, so checkpoint snapshots
  (taken at chunk boundaries) stay coherent with consumed input.

Requires the native parser; the pure-Python path has no multi-process
tier (it is not the deployment path).
"""

from __future__ import annotations

import multiprocessing
import pickle
import time
from multiprocessing import shared_memory

import numpy as np

from . import fastparse
from ..errors import FeedWorkerError, StallError
from ..runtime import faults, obs
from .pack import PackedRuleset, TUPLE_COLS, TUPLE6_COLS

#: Coordinator read granularity while scanning for batch boundaries.
SCAN_BLOCK = 8 << 20


def default_feed_workers() -> int:
    return fastparse.host_workers("RA_FEED_WORKERS", 16)


def _scan_batches(paths: list[str], batch_size: int, skip_lines: int):
    """Yield (path_idx, offset, nbytes, n_lines) descriptors.

    Each descriptor covers exactly ``batch_size`` raw lines (the final
    one per file may be short; descriptors never span files).  The first
    ``skip_lines`` lines are consumed without emitting (resume).
    """
    lib = fastparse._load()
    if lib is None:
        from ..errors import NativeParserUnavailable

        raise NativeParserUnavailable("feeder requires the native parser")
    import ctypes

    to_skip = skip_lines
    for path_i, path in enumerate(paths):
        with open(path, "rb") as f:
            buf = b""
            base = 0  # file offset of buf[0]
            pos = 0  # consumed bytes within buf
            eof = False
            pend_lines = 0  # lines in the current (incomplete) descriptor
            pend_start = 0  # absolute file offset where it starts

            def refill():
                nonlocal buf, base, pos, eof
                block = f.read(SCAN_BLOCK)
                if not block:
                    eof = True
                    return
                buf = buf[pos:] + block
                base += pos
                pos = 0

            while True:
                avail = len(buf) - pos
                if avail == 0:
                    if eof:
                        break
                    refill()
                    continue
                want = to_skip if to_skip > 0 else batch_size - pend_lines
                # zero-copy pointer into buf at pos (buf outlives the call)
                arr = np.frombuffer(buf, dtype=np.uint8)
                used = ctypes.c_int64(0)
                got = int(
                    lib.asa_count_lines(
                        ctypes.c_void_p(arr.ctypes.data + pos), avail,
                        1 if eof else 0, want, ctypes.byref(used),
                    )
                )
                if got == 0:
                    if eof:
                        break
                    refill()  # a line longer than the buffered bytes
                    continue
                if to_skip > 0:
                    to_skip -= got
                    pos += int(used.value)
                    continue
                if pend_lines == 0:
                    pend_start = base + pos
                pend_lines += got
                pos += int(used.value)
                if pend_lines == batch_size:
                    yield (path_i, pend_start, base + pos - pend_start, pend_lines)
                    pend_lines = 0
            if pend_lines:
                yield (path_i, pend_start, base + pos - pend_start, pend_lines)
    if to_skip > 0:
        from ..errors import ResumeInputMismatch

        raise ResumeInputMismatch(
            f"snapshot consumed {skip_lines} lines but the input ran short "
            f"by {to_skip}"
        )


def _worker(packed_blob, paths, rows_cap, rows6_cap, shm_name, task_q, done_q):
    # span shards arm lazily from the inherited RA_TRACE_DIR (the same
    # env channel the fault plan rides); the label makes this process's
    # track readable in the merged timeline
    obs.note_role("feeder-worker")
    packed = pickle.loads(packed_blob)
    packer = fastparse.NativePacker(packed)
    shm = shared_memory.SharedMemory(name=shm_name)
    slot_words = TUPLE_COLS * rows_cap + TUPLE6_COLS * rows6_cap
    files = {}
    try:
        while True:
            task = task_q.get()
            if task is None:
                return
            t0_span = time.perf_counter()
            # fault sites (plan arrives via the inherited RA_FAULT_PLAN
            # env): abrupt death — the OOM-kill the coordinator's
            # liveness probe must catch — and a wedge the coordinator's
            # stall watchdog must bound
            faults.fire("feeder.worker.crash")
            faults.fire("feeder.worker.stall")
            idx, slot, path_i, offset, nbytes, n_lines = task
            try:
                f = files.get(path_i)
                if f is None:
                    f = files[path_i] = open(paths[path_i], "rb")
                f.seek(offset)
                data = f.read(nbytes)
                out = np.ndarray(
                    (TUPLE_COLS, rows_cap), dtype=np.uint32, buffer=shm.buf,
                    offset=4 * slot * slot_words,
                )
                p0, s0 = packer.parsed, packer.skipped
                _, lines, _used = packer.pack_chunk(
                    data, rows_cap, final=True, max_lines=n_lines, n_threads=1,
                    out=out,
                )
                n6 = 0
                if rows6_cap:
                    # v6 rows the dual-family parse staged for this range
                    # ride the slot's second plane (input order preserved:
                    # the coordinator attributes them when idx yields)
                    rows6 = packer.take_v6()
                    n6 = len(rows6)
                    if n6:
                        plane6 = np.ndarray(
                            (TUPLE6_COLS, rows6_cap), dtype=np.uint32,
                            buffer=shm.buf,
                            offset=4 * (slot * slot_words + TUPLE_COLS * rows_cap),
                        )
                        plane6[:, :n6] = np.asarray(rows6, dtype=np.uint32).T
            except Exception as e:  # forward instead of dying silently
                done_q.put(("error", idx, f"{type(e).__name__}: {e}"))
                return
            obs.complete(
                "feeder.parse", t0_span, time.perf_counter(), cat="feeder",
                args={"batch": idx, "lines": lines},
            )
            done_q.put(
                (idx, slot, lines, packer.parsed - p0, packer.skipped - s0, n6)
            )
    finally:
        # seal this worker's flight ring (no-op disarmed): if the RUN
        # aborts — e.g. a sibling was SIGKILL'd — the supervising merge
        # reads the survivors' telemetry; a clean run prunes every seal
        from ..runtime import flightrec

        flightrec.seal()
        for f in files.values():
            f.close()
        shm.close()


class _FeedCounters:
    def __init__(self):
        self.parsed = 0
        self.skipped = 0


class _FeederBase:
    """Shared source-protocol state of the multi-worker feed tiers.

    Both tiers commit worker completions in input order: parsed/skipped
    deltas fold into ``.packer`` and v6 rows stage for ``take_v6`` only
    when their batch is YIELDED, so checkpoint snapshots stay coherent
    with consumed input no matter how far workers ran ahead.
    """

    def __init__(
        self,
        packed: PackedRuleset,
        paths: list[str],
        n_workers: int | None = None,
        stall_timeout: float | None = None,
    ):
        if not fastparse.available():
            from ..errors import NativeParserUnavailable

            raise NativeParserUnavailable("feeder requires the native parser")
        self.packed = packed
        self.paths = list(paths)
        self.n_workers = n_workers or default_feed_workers()
        #: watchdog bound: workers alive but completing nothing for this
        #: long is a wedge, escalated to a typed StallError abort
        self.stall_timeout = (
            stall_timeout if stall_timeout and stall_timeout > 0
            else faults.default_stall_timeout()
        )
        self.packer = _FeedCounters()
        self._resume_counts = (0, 0)
        self._v6chunks: list[np.ndarray] = []  # [n,13] arrays, input order
        #: digest -> 128-bit source for talker rendering (same contract
        #: as the other sources)
        self.v6_digests: dict[int, int] = {}

    def set_counts(self, parsed: int, skipped: int) -> None:
        self._resume_counts = (parsed, skipped)

    def take_v6(self):
        """Staged v6 rows as one [n, 13] array (or [] when none)."""
        chunks = self._v6chunks
        self._v6chunks = []
        if not chunks:
            return []
        if len(chunks) == 1:
            return chunks[0]
        return np.concatenate(chunks)

    def _stage_v6(self, rows6: np.ndarray) -> None:
        """Commit one batch's v6 rows + talker digests, in input order."""
        from .pack import T6_SRC, V6_DIGEST_CAP, fold_src32_host, limbs_u128

        dig = self.v6_digests
        for r in rows6:
            if len(dig) >= V6_DIGEST_CAP:
                break
            src = limbs_u128(*r[T6_SRC:T6_SRC + 4])
            dig.setdefault(fold_src32_host(src), src)
        self._v6chunks.append(rows6)


class ParallelFeeder(_FeederBase):
    """Stream-source over files backed by N parse worker processes.

    Drop-in for the stream driver's source protocol: ``.packer`` exposes
    parsed/skipped counters and ``.batches(skip_lines, batch_size)``
    yields ``([TUPLE_COLS, rows_cap] uint32, raw_line_count)`` in input
    order.  ``rows_cap`` is fixed per run (2x batch_size with
    out-bindings), so one compiled device program serves every chunk.
    """

    def batches(self, skip_lines: int, batch_size: int):
        self.packer.parsed, self.packer.skipped = self._resume_counts
        rows_cap = (2 if self.packed.bindings_out else 1) * batch_size
        # v6 plane: any line of a batch can be a dual-evaluation v6 line
        rows6_cap = 2 * batch_size if self.packed.has_v6 else 0
        n_slots = 2 * self.n_workers + 2
        slot_bytes = 4 * (TUPLE_COLS * rows_cap + TUPLE6_COLS * rows6_cap)
        shm = shared_memory.SharedMemory(create=True, size=n_slots * slot_bytes)
        # spawn, not fork: the driver process runs JAX's thread pools, and
        # forking a multi-threaded process can deadlock the child.  The
        # workers import only numpy + the native parser, so spawn is cheap.
        ctx = multiprocessing.get_context("spawn")
        task_q = ctx.Queue()
        done_q = ctx.Queue()
        blob = pickle.dumps(self.packed)
        workers = [
            ctx.Process(
                target=_worker,
                args=(blob, self.paths, rows_cap, rows6_cap, shm.name,
                      task_q, done_q),
                daemon=True,
            )
            for _ in range(self.n_workers)
        ]
        for w in workers:
            w.start()
        self._workers = workers  # exposed for fault-injection tests
        try:
            free_slots = list(range(n_slots))
            ready: dict[int, tuple] = {}  # idx -> completion
            next_submit = 0
            next_yield = 0
            desc_it = _scan_batches(self.paths, batch_size, skip_lines)
            descs_done = False

            def submit_until_full():
                nonlocal next_submit, descs_done
                while free_slots and not descs_done:
                    d = next(desc_it, None)
                    if d is None:
                        descs_done = True
                        break
                    slot = free_slots.pop()
                    task_q.put((next_submit, slot, *d))
                    next_submit += 1

            import queue as _queue

            def _occupancy() -> dict:
                # pool gauges for the metrics snapshotter: how many
                # descriptors are in flight vs workers still alive
                return {
                    "mode": "process",
                    "workers": len(workers),
                    "alive": sum(1 for w in workers if w.is_alive()),
                    "inflight": next_submit - next_yield,
                    "ready": len(ready),
                    "free_slots": len(free_slots),
                }

            obs.register_sampler("feeder", _occupancy)
            submit_until_full()
            stall_deadline = time.monotonic() + self.stall_timeout
            while next_yield < next_submit:
                while next_yield not in ready:
                    # timeout + liveness: a worker killed by the OS (OOM)
                    # can't forward its error, and waiting forever on its
                    # completion would hang the whole analysis silently
                    try:
                        msg = done_q.get(timeout=5.0)
                    except _queue.Empty:
                        dead = [w.pid for w in workers if not w.is_alive()]
                        if dead:
                            raise FeedWorkerError(
                                f"feeder worker(s) {dead} died without "
                                "reporting (killed by the OS?)"
                            )
                        if time.monotonic() > stall_deadline:
                            # alive but completing nothing: a wedged
                            # worker (stuck I/O, injected stall) must be
                            # a bounded typed abort, not a silent hang
                            raise StallError(
                                f"feeder workers made no progress in "
                                f"{self.stall_timeout:.0f}s "
                                f"({len(workers)} alive); raise "
                                "--stall-timeout if the input is "
                                "legitimately this slow"
                            )
                        continue
                    # progress: any completion resets the stall window
                    stall_deadline = time.monotonic() + self.stall_timeout
                    if msg[0] == "error":
                        raise FeedWorkerError(
                            f"feeder worker failed on batch {msg[1]}: {msg[2]}"
                        )
                    idx, slot, lines, dp, ds, n6 = msg
                    ready[idx] = (slot, lines, dp, ds, n6)
                slot, lines, dp, ds, n6 = ready.pop(next_yield)
                slot_words = TUPLE_COLS * rows_cap + TUPLE6_COLS * rows6_cap
                out = np.ndarray(
                    (TUPLE_COLS, rows_cap), dtype=np.uint32, buffer=shm.buf,
                    offset=4 * slot * slot_words,
                ).copy()  # the slot is reused; the driver may hold the batch
                if n6:
                    plane6 = np.ndarray(
                        (TUPLE6_COLS, rows6_cap), dtype=np.uint32,
                        buffer=shm.buf,
                        offset=4 * (slot * slot_words + TUPLE_COLS * rows_cap),
                    )
                    self._stage_v6(np.ascontiguousarray(plane6[:, :n6].T))
                free_slots.append(slot)
                next_yield += 1
                self.packer.parsed += dp
                self.packer.skipped += ds
                submit_until_full()
                yield out, lines
        finally:
            obs.unregister_sampler("feeder")
            # Bounded teardown, also on a consumer-side exception: poison
            # pills, ONE shared join budget (a wedged worker must not
            # serialize N x 10s), terminate + reap stragglers, and close
            # the queues so their feeder threads don't outlive the run.
            for _ in workers:
                task_q.put(None)
            deadline = time.monotonic() + 10.0
            for w in workers:
                w.join(timeout=max(0.0, deadline - time.monotonic()))
            for w in workers:
                if w.is_alive():
                    w.terminate()
            for w in workers:
                w.join(timeout=5)
            for q in (task_q, done_q):
                q.cancel_join_thread()
                q.close()
            shm.close()
            shm.unlink()


def _ring_worker(packed_blob, paths, rows_cap_shard, rows6_cap_shard,
                 ring_depth, shm_name, task_q, done_q):
    """Ring-partition parse worker: fine descriptors -> per-chip slots.

    Each task names the chip (ring) and slot its output belongs to; the
    worker parses the descriptor's byte range straight into that slot's
    shared-memory planes.  One worker may own several rings (W < D) or
    share a ring with siblings (W > D); the coordinator's routing keeps
    every ring's slots written in group order either way.
    """
    obs.note_role("ring-worker")
    packed = pickle.loads(packed_blob)
    packer = fastparse.NativePacker(packed)
    shm = shared_memory.SharedMemory(name=shm_name)
    slot_words = (
        TUPLE_COLS * rows_cap_shard + TUPLE6_COLS * rows6_cap_shard
    )
    files = {}
    try:
        while True:
            task = task_q.get()
            if task is None:
                return
            t0_span = time.perf_counter()
            # the ring twin of the queue tier's fault sites, plus the
            # ring-specific stall: a wedged partition producer starves
            # exactly one chip — the coordinator must bound it
            faults.fire("feeder.worker.crash")
            faults.fire("feeder.ring.stall")
            g, j, slot, path_i, offset, nbytes, n_lines = task
            try:
                f = files.get(path_i)
                if f is None:
                    f = files[path_i] = open(paths[path_i], "rb")
                f.seek(offset)
                data = f.read(nbytes)
                slot_off = 4 * (j * ring_depth + slot) * slot_words
                out = np.ndarray(
                    (TUPLE_COLS, rows_cap_shard), dtype=np.uint32,
                    buffer=shm.buf, offset=slot_off,
                )
                p0, s0 = packer.parsed, packer.skipped
                _, lines, _used = packer.pack_chunk(
                    data, rows_cap_shard, final=True, max_lines=n_lines,
                    n_threads=1, out=out,
                )
                n6 = 0
                if rows6_cap_shard:
                    rows6 = packer.take_v6()
                    n6 = len(rows6)
                    if n6:
                        plane6 = np.ndarray(
                            (TUPLE6_COLS, rows6_cap_shard), dtype=np.uint32,
                            buffer=shm.buf,
                            offset=slot_off + 4 * TUPLE_COLS * rows_cap_shard,
                        )
                        plane6[:, :n6] = np.asarray(rows6, dtype=np.uint32).T
            except Exception as e:  # forward instead of dying silently
                done_q.put(("error", g, f"{type(e).__name__}: {e}"))
                return
            obs.complete(
                "feeder.parse", t0_span, time.perf_counter(), cat="feeder",
                args={"group": g, "ring": j, "lines": lines},
            )
            done_q.put(
                (g, j, slot, lines, packer.parsed - p0, packer.skipped - s0,
                 n6)
            )
    finally:
        # worker-exit seal, exactly like the queue-tier worker above
        from ..runtime import flightrec

        flightrec.seal()
        for f in files.values():
            f.close()
        shm.close()


class _RingBatch:
    """One committed group: per-chip zero-copy views of ring slots.

    ``views[d]`` is chip d's ``[TUPLE_COLS, shard_rows]`` plane, a view
    STRAIGHT INTO that chip's shared-memory ring slot.  The consumer
    must call :meth:`release` once it has copied the data out (the wire
    bit-pack copies, so the per-chip ``device_put`` path releases right
    after compacting); :meth:`assemble` is the copy-and-release
    convenience for consumers that want one plain batch.
    """

    __slots__ = ("views", "n_raw", "_release_cb", "released")

    def __init__(self, views, n_raw, release_cb):
        self.views = views
        self.n_raw = n_raw
        self._release_cb = release_cb
        self.released = False

    def release(self) -> None:
        if not self.released:
            self.released = True
            self._release_cb()

    def assemble(self) -> np.ndarray:
        """Concatenate to one ``[TUPLE_COLS, D*shard_rows]`` batch
        (copies, then releases the ring slots)."""
        out = np.concatenate(self.views, axis=1)
        self.release()
        return out


class RingFeeder(_FeederBase):
    """Per-chip feeder rings: one shared-memory ring per device.

    The global task/completion queue of :class:`ParallelFeeder` funnels
    every batch through one coordinator copy and one whole-batch
    ``device_put`` — a host-side serialization point an 8-chip mesh
    outgrows.  This tier partitions the producer pool BY CHIP instead
    (ISSUE 11; the per-host data-tier idiom of the hybrid DCN x ICI
    mesh): each device d owns a ring of ``ring_depth`` shared-memory
    slots, descriptors chop ``batch_size/D`` lines fine (so a group of D
    consecutive descriptors covers exactly the lines a queue-tier batch
    would), and the worker partition serving ring d parses its line
    sub-ranges straight into d's slots.  The driver's pack stage then
    bit-packs each chip's view and issues that chip's ``device_put``
    directly from the ring — no global assembly, no coordinator copy.

    Equivalence with the queue tier: a group covers the same raw lines
    as the queue batch with the same index (groups reset at file
    boundaries exactly like batches), every register update is
    order/padding-invariant, and v6 rows commit in line order through
    the same rings — reports are bit-identical (pinned in
    tests/test_feeder.py).  Within a group, chip d's shard holds the
    rows of line sub-range d with its own valid prefix; padding between
    shards is masked on device like any other padding.

    ``emit_views`` (set by the driver): True yields :class:`_RingBatch`
    per-chip views for the direct ``device_put`` path (flat layout +
    prefetch); False yields plain assembled ``[TUPLE_COLS, rows_cap]``
    arrays so the sync driver and the stacked layout consume this tier
    unchanged.
    """

    yields_ring = True

    def __init__(
        self,
        packed: PackedRuleset,
        paths: list[str],
        n_workers: int | None = None,
        stall_timeout: float | None = None,
        n_rings: int | None = None,
        ring_depth: int = 4,
    ):
        super().__init__(packed, paths, n_workers, stall_timeout)
        #: one ring per device; the driver resolves None to the mesh's
        #: data extent before pulling batches
        self.n_rings = n_rings
        self.ring_depth = max(2, ring_depth)
        self.emit_views = False
        #: per-ring starved seconds (coordinator waited on this chip's
        #: shard) — the trace_summary feed block's starved-chip gauge
        self._starved_sec: list[float] = []
        self._occupancy: list[int] = []

    def batches(self, skip_lines: int, batch_size: int):
        self.packer.parsed, self.packer.skipped = self._resume_counts
        D = int(self.n_rings or 1)
        if batch_size % D:
            from ..errors import AnalysisError

            raise AnalysisError(
                f"ring feeder needs batch_size divisible by the ring count "
                f"({batch_size} % {D} != 0); pad the batch size"
            )
        sub = batch_size // D
        rows_cap_shard = (2 if self.packed.bindings_out else 1) * sub
        rows6_cap_shard = 2 * sub if self.packed.has_v6 else 0
        R = self.ring_depth
        W = self.n_workers
        slot_words = TUPLE_COLS * rows_cap_shard + TUPLE6_COLS * rows6_cap_shard
        shm = shared_memory.SharedMemory(
            create=True, size=4 * D * R * slot_words
        )
        ctx = multiprocessing.get_context("spawn")
        # one producer pool partition per chip: ring d is served by a
        # fixed worker set — contiguous ring blocks when W < D, the
        # w ≡ d (mod D) residue class when W >= D — so chip d's feed
        # never contends with another chip's parse backlog
        if W >= D:
            ring_workers = [[w for w in range(W) if w % D == d]
                            for d in range(D)]
        else:
            ring_workers = [[d * W // D] for d in range(D)]
        used_workers = sorted({w for ws in ring_workers for w in ws})
        task_qs = {w: ctx.Queue() for w in used_workers}
        done_q = ctx.Queue()
        blob = pickle.dumps(self.packed)
        workers = {
            w: ctx.Process(
                target=_ring_worker,
                args=(blob, self.paths, rows_cap_shard, rows6_cap_shard, R,
                      shm.name, task_qs[w], done_q),
                daemon=True,
            )
            for w in used_workers
        }
        for w in workers.values():
            w.start()
        self._workers = list(workers.values())  # fault-injection tests
        self._starved_sec = [0.0] * D
        self._occupancy = [0] * D
        import queue as _queue

        next_submit = 0  # defined before try: the finally reads them
        next_yield = 0
        t_feed0 = None
        occ_integral = [0.0] * D
        try:
            free_slots = [list(range(R)) for _ in range(D)]
            # group bookkeeping: meta[g] = (n_shards, n_raw); done[g] =
            # {j: (slot, lines, dp, ds, n6)}
            meta: dict[int, tuple[int, int]] = {}
            done: dict[int, dict[int, tuple]] = {}

            def group_it():
                """Yield [descriptors] groups of <= D fine descriptors,
                resetting at file boundaries (exactly the line spans the
                queue tier's batch_size-line batches cover)."""
                cur: list[tuple] = []
                for d in _scan_batches(self.paths, sub, skip_lines):
                    if cur and (d[0] != cur[0][0] or len(cur) == D):
                        yield cur
                        cur = []
                    cur.append(d)
                    if d[3] < sub:  # short descriptor: file ends here
                        yield cur
                        cur = []
                if cur:
                    yield cur

            groups = group_it()
            groups_done = False

            def submit_until_full():
                # a group submits only when EVERY ring it touches has a
                # free slot, so submission order per ring == group order
                nonlocal next_submit, groups_done
                while not groups_done:
                    if any(not free_slots[j] for j in range(D)):
                        return
                    grp = next(groups, None)
                    if grp is None:
                        groups_done = True
                        return
                    g = next_submit
                    next_submit += 1
                    meta[g] = (len(grp), sum(d[3] for d in grp))
                    done.setdefault(g, {})
                    for j, desc in enumerate(grp):
                        slot = free_slots[j].pop()
                        self._occupancy[j] += 1
                        ws = ring_workers[j]
                        task_qs[ws[g % len(ws)]].put((g, j, slot, *desc))

            def _gauges() -> dict:
                occ = list(self._occupancy)
                return {
                    "mode": "ring",
                    "rings": D,
                    "ring_depth": R,
                    "workers": len(workers),
                    "alive": sum(1 for w in workers.values() if w.is_alive()),
                    "inflight": next_submit - next_yield,
                    "ring_occupancy": occ,
                    "partition_imbalance": max(occ) - min(occ) if occ else 0,
                    "starved_sec": [round(s, 3) for s in self._starved_sec],
                }

            obs.register_sampler("feeder", _gauges)
            submit_until_full()
            t_feed0 = time.monotonic()  # occupancy integral starts here
            t_occ = t_feed0
            stall_deadline = time.monotonic() + self.stall_timeout
            while True:
                if next_yield == next_submit:
                    if groups_done:
                        break
                    # input remains but nothing could submit: the consumer
                    # still holds every slot of some ring, and releases can
                    # only happen on the consumer's own thread between
                    # pulls — progress is impossible from inside this
                    # generator, so abort loudly rather than silently
                    # truncating the corpus at this point
                    raise FeedWorkerError(
                        "ring slots exhausted with unparsed input left: "
                        "the consumer holds batches for every slot of a "
                        "ring; release each batch before pulling the next "
                        "(or raise ring_depth)"
                    )
                n_shards, n_raw = meta[next_yield]
                while len(done[next_yield]) < n_shards:
                    pending = [
                        j for j in range(n_shards)
                        if j not in done[next_yield]
                    ]
                    t0 = time.monotonic()
                    try:
                        msg = done_q.get(timeout=5.0)
                    except _queue.Empty:
                        dt = time.monotonic() - t0
                        for j in pending:
                            self._starved_sec[j] += dt
                        dead = [
                            w.pid for w in workers.values()
                            if not w.is_alive()
                        ]
                        if dead:
                            raise FeedWorkerError(
                                f"ring feed worker(s) {dead} died without "
                                "reporting (killed by the OS?)"
                            )
                        if time.monotonic() > stall_deadline:
                            starving = ", ".join(
                                f"chip{j}" for j in pending[:4]
                            )
                            raise StallError(
                                f"ring feed made no progress in "
                                f"{self.stall_timeout:.0f}s (rings dry: "
                                f"{starving}); raise --stall-timeout if "
                                "the input is legitimately this slow"
                            )
                        continue
                    now = time.monotonic()
                    dt = now - t0
                    for j in pending:
                        self._starved_sec[j] += dt
                    for j in range(D):  # occupancy integral (slot-seconds)
                        occ_integral[j] += self._occupancy[j] * (now - t_occ)
                    t_occ = now
                    stall_deadline = time.monotonic() + self.stall_timeout
                    if msg[0] == "error":
                        raise FeedWorkerError(
                            f"ring feed worker failed on group {msg[1]}: "
                            f"{msg[2]}"
                        )
                    g, j, slot, lines, dp, ds, n6 = msg
                    done[g][j] = (slot, lines, dp, ds, n6)
                shards = done.pop(next_yield)
                meta.pop(next_yield)
                views = []
                taken: list[tuple[int, int]] = []  # (ring, slot) to free
                for j in range(n_shards):
                    slot, lines, dp, ds, n6 = shards[j]
                    slot_off = 4 * (j * R + slot) * slot_words
                    views.append(np.ndarray(
                        (TUPLE_COLS, rows_cap_shard), dtype=np.uint32,
                        buffer=shm.buf, offset=slot_off,
                    ))
                    if n6:
                        plane6 = np.ndarray(
                            (TUPLE6_COLS, rows6_cap_shard), dtype=np.uint32,
                            buffer=shm.buf,
                            offset=slot_off + 4 * TUPLE_COLS * rows_cap_shard,
                        )
                        # committed in shard (= line) order, same stream
                        # as the queue tier stages
                        self._stage_v6(
                            np.ascontiguousarray(plane6[:, :n6].T)
                        )
                    self.packer.parsed += dp
                    self.packer.skipped += ds
                    taken.append((j, slot))
                for j in range(n_shards, D):
                    # short group (file end): missing chips feed zeros —
                    # valid=0 padding, masked on device like any other
                    views.append(np.zeros(
                        (TUPLE_COLS, rows_cap_shard), dtype=np.uint32
                    ))

                def release(taken=taken):
                    for j, slot in taken:
                        free_slots[j].append(slot)
                        self._occupancy[j] -= 1

                rb = _RingBatch(views, n_raw, release)
                next_yield += 1
                if not self.emit_views:
                    out = rb.assemble()  # copies + releases before yield
                    submit_until_full()
                    yield out, n_raw
                else:
                    yield rb, n_raw
                    # the consumer released during pack (same thread);
                    # anything still held just waits another round
                    submit_until_full()
        finally:
            obs.unregister_sampler("feeder")
            # one summary instant on the obs timeline (the devprof.summary
            # pattern): the trace_summary feed block renders these without
            # needing the metrics JSONL
            if next_submit and t_feed0 is not None:
                elapsed = max(1e-9, time.monotonic() - t_feed0)
                occ_pct = [
                    round(100.0 * occ_integral[j] / (R * elapsed), 2)
                    for j in range(D)
                ]
                obs.instant(
                    "feeder.summary",
                    args={
                        "mode": "ring",
                        "rings": D,
                        "ring_depth": R,
                        "workers": len(workers),
                        "groups": next_yield,
                        "ring_occupancy_pct": occ_pct,
                        "partition_imbalance_pct": round(
                            max(occ_pct) - min(occ_pct), 2
                        ) if occ_pct else 0.0,
                        "starved_sec": [
                            round(s, 3) for s in self._starved_sec
                        ],
                        "starved_total_sec": round(
                            sum(self._starved_sec), 3
                        ),
                    },
                )
            for w_id, q in task_qs.items():
                q.put(None)
            deadline = time.monotonic() + 10.0
            for w in workers.values():
                w.join(timeout=max(0.0, deadline - time.monotonic()))
            for w in workers.values():
                if w.is_alive():
                    w.terminate()
            for w in workers.values():
                w.join(timeout=5)
            for q in (*task_qs.values(), done_q):
                q.cancel_join_thread()
                q.close()
            try:
                shm.close()
            except BufferError:
                # a consumer still holds a zero-copy slot view (e.g. an
                # exception unwound mid-pack); dropping our reference
                # lets GC finalize the mapping once the view dies — and
                # teardown must not mask the consumer's real error
                pass
            shm.unlink()


class ThreadedFeeder(_FeederBase):
    """In-process threaded twin of :class:`ParallelFeeder`.

    Worker THREADS parse the same exact-raw-line byte-range descriptors
    the coordinator scans; the native parser releases the GIL for the
    parse itself, so threads scale across cores with no spawn cost, no
    pickling, and no shared-memory plumbing — the tier of choice when
    the driver process can spare cores (the prefetching ingest engine
    stacks on top, overlapping whichever tier feeds it with the device
    step).  Each thread builds ONE NativePacker lazily (the gid tables
    are per-thread, reused across its descriptors); completions commit
    strictly in input order with their parsed/skipped deltas and staged
    v6 rows, so batch boundaries — and the top-K caveat — are identical
    to the process tier over the same input.
    """

    def batches(self, skip_lines: int, batch_size: int):
        import concurrent.futures as cf
        import threading

        self.packer.parsed, self.packer.skipped = self._resume_counts
        rows_cap = (2 if self.packed.bindings_out else 1) * batch_size
        has_v6 = self.packed.has_v6
        tl = threading.local()
        # every handle any worker thread opens, for deterministic release
        # in the finally below (thread-local GC alone would hold fds open
        # past an early consumer exit — the same discipline _run_core's
        # close() applies to wire mmaps)
        files_lock = threading.Lock()
        opened: list = []

        stop_ev = threading.Event()  # releases injected stalls at teardown

        def work(desc):
            t0_span = time.perf_counter()
            # thread-tier twin of the process worker's fault sites (no
            # crash site: os._exit here would take the driver down)
            faults.fire("feeder.worker.stall", stop=stop_ev)
            path_i, offset, nbytes, n_lines = desc
            pk = getattr(tl, "packer", None)
            if pk is None:
                pk = tl.packer = fastparse.NativePacker(self.packed)
                tl.files = {}
            f = tl.files.get(path_i)
            if f is None:
                f = tl.files[path_i] = open(self.paths[path_i], "rb")
                with files_lock:
                    opened.append(f)
            f.seek(offset)
            data = f.read(nbytes)
            p0, s0 = pk.parsed, pk.skipped
            batch, lines, _used = pk.pack_chunk(
                data, rows_cap, final=True, max_lines=n_lines, n_threads=1
            )
            rows6 = pk.take_v6() if has_v6 else []
            obs.complete(
                "feeder.parse", t0_span, time.perf_counter(), cat="feeder",
                args={"lines": lines},
            )
            return batch, lines, pk.parsed - p0, pk.skipped - s0, rows6

        from collections import deque

        desc_it = _scan_batches(self.paths, batch_size, skip_lines)
        ex = cf.ThreadPoolExecutor(
            max_workers=self.n_workers, thread_name_prefix="ra-feed"
        )
        inflight: deque = deque()
        max_inflight = 2 * self.n_workers + 2
        stalled = False
        try:
            obs.register_sampler(
                "feeder",
                lambda: {
                    "mode": "thread",
                    "workers": self.n_workers,
                    "inflight": len(inflight),
                },
            )

            def fill() -> None:
                while len(inflight) < max_inflight:
                    d = next(desc_it, None)
                    if d is None:
                        return
                    inflight.append(ex.submit(work, d))

            fill()
            while inflight:
                fut = inflight.popleft()
                try:
                    # stall watchdog: a worker thread that wedges (stuck
                    # I/O, injected stall) bounds to a typed abort — the
                    # batches commit in submission order, so waiting on
                    # THIS future is exactly producer-to-consumer progress
                    batch, lines, dp, ds, rows6 = fut.result(
                        timeout=self.stall_timeout
                    )
                except cf.TimeoutError:
                    stalled = True
                    raise StallError(
                        f"feed worker made no progress in "
                        f"{self.stall_timeout:.0f}s; raise --stall-timeout "
                        "if the input is legitimately this slow"
                    ) from None
                except Exception as e:
                    raise FeedWorkerError(
                        f"feed worker failed: {type(e).__name__}: {e}"
                    ) from e
                self.packer.parsed += dp
                self.packer.skipped += ds
                if len(rows6):
                    self._stage_v6(np.asarray(rows6, dtype=np.uint32))
                fill()
                yield batch, lines
        finally:
            obs.unregister_sampler("feeder")
            # release injected stalls FIRST so the bounded shutdown below
            # cannot wedge on a thread parked in a fault site
            stop_ev.set()
            # wait: a worker mid-descriptor must finish before its file
            # handles close under it (each task is one bounded parse).
            # EXCEPT after a stall verdict: a thread wedged in an OS call
            # cannot be cancelled, and waiting on it would turn the typed
            # StallError into the very hang the watchdog exists to
            # prevent — abandon it (the process tier, which CAN terminate
            # its workers, is the tier of choice for hostile inputs)
            ex.shutdown(wait=not stalled, cancel_futures=True)
            with files_lock:
                for f in opened:
                    f.close()
