"""On-disk wire format: pre-tokenized syslog, 16 bytes/line, mmap-readable.

SURVEY.md §8.2 names host regex parse as the end-to-end bottleneck and
prescribes a "pre-tokenized binary input format for the benchmark path".
This module makes that format a production tier, not a bench-only hack:

- ``ruleset-analyze convert`` parses text syslog ONCE (native C++ parser
  when available) and writes a ``.rawire`` file holding each ACL
  evaluation as the same 4-word bit-packed row that crosses the
  host->device link (``pack.compact_batch``: src | dst | sport<<16|dport |
  proto<<24|valid<<23|acl).  Re-running an analysis then skips the parse
  entirely — the mmap-backed reader feeds the device step at memory
  bandwidth, which is what lets a small host keep a TPU busy.

- The file is bound to the ruleset it was packed against: ACL gids are
  ruleset-relative, so the header carries a ruleset fingerprint and the
  reader refuses a mismatched ruleset instead of silently attributing
  hits to the wrong ACLs.

Layout (all little-endian):

  header, 64 bytes:
    0   magic     8s   b"RAWIREv1"
    8   block_rows u32  rows per payload block
    12  reserved  u32
    16  n_rows    u64  total evaluation rows in the payload
    24  raw_lines u64  raw text lines the converter consumed
    32  n_evals   u64  evaluations emitted (== n_rows)
    40  n_skipped u64  raw lines that produced no evaluation
    48  fp        16s  ruleset fingerprint (sha256 prefix)
  payload: ceil(n_rows / block_rows) blocks; block b holds
    r = min(block_rows, n_rows - b*block_rows) rows stored column-major
    as a C-contiguous [WIRE_COLS, r] uint32 plane — so a whole block is a
    zero-copy mmap slice ready for jax.device_put.

Only evaluation rows are stored (a skipped line would be 16 zero bytes of
padding the device masks out anyway); the header keeps the raw-line
accounting so reports state true input totals.  Rows appear in exactly
the order the text path would evaluate them, so registers and per-rule
counts from a ``.rawire`` run are bit-identical to the text run.
"""

from __future__ import annotations

import hashlib
import mmap
import os
import struct
from collections.abc import Iterator

import numpy as np

from ..errors import AnalysisError
from .pack import (
    T_VALID,
    TUPLE_COLS,
    W_META,
    W_WEIGHT,
    W6_WEIGHT,
    WIRE_COLS,
    WIRE6_COLS,
    WIRE6W_COLS,
    WIREW_COLS,
    PackedRuleset,
    coalesce_wire,
    coalesce_wire6,
    compact_batch,
    compact_batch6,
)

MAGIC = b"RAWIREv1"
#: Wire format v2 (DESIGN.md "IPv6 position"): a second payload section
#: of IPv6 rows (40 B/line) follows the v4 blocks.  The writer only
#: upgrades to v2 when a v6 row was actually written, so all-v4 corpora
#: keep producing byte-identical v1 files; readers sniff by magic.
MAGIC6 = b"RAWIREv2"
#: Wire format v3 (ISSUE 5): COALESCED rows — every stored row is a
#: distinct evaluation tuple carrying a uint32 weights plane (20 B/row
#: v4, 44 B/row v6; pack.WIREW_COLS/WIRE6W_COLS).  ``convert --coalesce``
#: writes it; the run path feeds the weighted rows straight to the
#: device, which reads the weights row as its valid plane.  v1/v2 files
#: are untouched (implicit weight = 1), and the header's ``n_evals``
#: keeps the TRUE evaluation count (summed weights) so reports state
#: original-input totals.  v3 always uses the 72-byte v2 header layout
#: (the v6 section row count is simply 0 for all-v4 corpora).
MAGIC_W = b"RAWIREv3"
#: Placeholder magic while a convert is in flight; only a successful
#: ``WireWriter.close()`` upgrades it to MAGIC, so a crashed or aborted
#: convert leaves a file every reader refuses ("not a wire file") instead
#: of a silently short one.
MAGIC_PARTIAL = b"RAWIRE??"
HEADER_BYTES = 64
_HEADER_FMT = "<8sII4Q16s"
#: v2 header: the v1 fields plus the v6-section row count.
HEADER6_BYTES = 72
_HEADER6_FMT = "<8sII5Q16s"
#: Default rows per payload block.  Matches the default run batch size so
#: the aligned read path hands mmap views straight to device_put.
DEFAULT_BLOCK_ROWS = 1 << 16

ROW_BYTES = WIRE_COLS * 4  # 16 B/line
ROW6_BYTES = 40  # WIRE6_COLS * 4
ROWW_BYTES = WIREW_COLS * 4  # 20 B/row (weighted v4)
ROW6W_BYTES = WIRE6W_COLS * 4  # 44 B/row (weighted v6)


def ruleset_fingerprint(packed: PackedRuleset) -> bytes:
    """16-byte identity of the gid universe a wire file is valid for.

    Covers everything that maps a log line to (acl gid, key): the expanded
    rule matrix, deny keys, ACL gid assignment, and interface bindings.
    """
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(packed.rules).tobytes())
    if packed.has_v6:
        # v6 rows change which evaluations a line produces, so they are
        # part of the identity; pure-v4 rulesets hash exactly as before
        # the v6 data model, keeping pre-v6 wire artifacts valid
        h.update(np.ascontiguousarray(packed.rules6).tobytes())
    h.update(np.ascontiguousarray(packed.deny_key).tobytes())
    for (fw, acl), gid in sorted(packed.acl_gid.items()):
        h.update(f"a:{fw}/{acl}={gid};".encode())
    for (fw, iface), gid in sorted(packed.bindings.items()):
        h.update(f"i:{fw}/{iface}={gid};".encode())
    for (fw, iface), gid in sorted(packed.bindings_out.items()):
        h.update(f"o:{fw}/{iface}={gid};".encode())
    return h.digest()[:16]


class WireFormatError(AnalysisError):
    """Bad magic, truncated payload, or ruleset mismatch."""


class WireWriter:
    """Stream evaluation rows into a ``.rawire`` file.

    Feed dense wire-format column batches (``[WIRE_COLS, k]`` uint32, all
    rows valid); blocks are written as they fill and the header is
    back-patched on close.  Until :meth:`close` succeeds the header
    carries ``MAGIC_PARTIAL``, so a convert that crashes, is interrupted,
    or calls :meth:`abort` leaves a file every reader refuses outright —
    never one that validates with only part of the rows.
    """

    def __init__(
        self,
        path: str,
        fp: bytes,
        block_rows: int = DEFAULT_BLOCK_ROWS,
        weighted: bool = False,
    ):
        if block_rows <= 0:
            raise ValueError("block_rows must be positive")
        self._path = path
        self._f = open(path, "wb")
        self._fp = fp
        self.block_rows = block_rows
        #: v3 coalesced format: rows carry a weights plane; ``n_evals``
        #: then tracks SUMMED weights (true evaluations), not stored rows
        self.weighted = weighted
        self._cols4 = WIREW_COLS if weighted else WIRE_COLS
        self._cols6 = WIRE6W_COLS if weighted else WIRE6_COLS
        self._evals = 0
        self.n_rows = 0
        self.n6_rows = 0
        self.raw_lines = 0
        self.n_skipped = 0
        self._buf = np.empty((self._cols4, block_rows), dtype=np.uint32)
        self._fill = 0
        #: v6 rows spill to a sibling temp file while v4 blocks stream to
        #: the main file (the v6 section must FOLLOW every v4 block); a
        #: successful close appends the spill and deletes it.  Memory
        #: stays one block per family regardless of corpus size.
        self._f6 = None
        self._buf6 = None
        self._fill6 = 0
        # The v2 header is longer; reserve the larger size up front so a
        # v6 row arriving late never forces a payload rewrite.  All-v4
        # closes rewind to the v1 64-byte header and the payload starts
        # at HEADER6_BYTES... which would break v1 readers, so instead
        # the HEADER SIZE is chosen by the first add: we always write the
        # v1-sized placeholder and, if v6 rows exist at close, rewrite
        # the file with the v2 header via a rename-free tail shuffle —
        # avoided entirely by just padding: v1 files put payload at 64,
        # v2 files at 72.  Since rows stream out as they arrive, the
        # choice must be made BEFORE the first v4 block lands; a ruleset
        # without v6 rows never calls add6, so the caller passes
        # has_v6 via begin6() before any add when v6 is possible.
        # (The weighted v3 format always reserves the 72-byte header.)
        self._payload_at = HEADER6_BYTES if weighted else HEADER_BYTES
        self._f.write(self._header(final=False))

    def begin6(self) -> None:
        """Declare that v6 rows MAY follow (call before the first add).

        Reserves the v2 header size.  A file that declared begin6 but saw
        no v6 rows still closes as v2 with an empty v6 section — readers
        handle n6_rows == 0, and all-v4 corpora (no begin6) keep their
        exact v1 bytes.
        """
        if self._payload_at == HEADER6_BYTES:
            return  # weighted files (or repeated calls) already reserved it
        if self.n_rows or self._fill or self.n6_rows:
            raise RuntimeError("begin6() must precede the first add")
        self._payload_at = HEADER6_BYTES
        self._f.seek(0)
        self._f.truncate()
        self._f.write(self._header(final=False))

    def _header(self, final: bool = True) -> bytes:
        if self._payload_at == HEADER6_BYTES:
            if self.weighted:
                magic = MAGIC_W if final else MAGIC_PARTIAL
                evals = self._evals  # summed weights = true evaluations
            else:
                magic = MAGIC6 if final else MAGIC_PARTIAL
                evals = self.n_rows + self.n6_rows  # n_evals == stored rows
            return struct.pack(
                _HEADER6_FMT,
                magic,
                self.block_rows,
                0,
                self.n_rows,
                self.n6_rows,
                self.raw_lines,
                evals,
                self.n_skipped,
                self._fp,
            )
        return struct.pack(
            _HEADER_FMT,
            MAGIC if final else MAGIC_PARTIAL,
            self.block_rows,
            0,
            self.n_rows,
            self.raw_lines,
            self.n_rows,  # n_evals == stored rows
            self.n_skipped,
            self._fp,
        )

    def add(self, wire: np.ndarray, raw_lines: int, skipped: int) -> None:
        """Append ``wire[:, :k]`` rows covering ``raw_lines`` text lines.

        Weighted writers take ``[WIREW_COLS, k]`` planes (weights row
        included) and fold the summed weights into ``n_evals``.
        """
        if wire.dtype != np.uint32 or wire.shape[0] != self._cols4:
            raise ValueError(
                f"expected [{self._cols4}, k] uint32, got {wire.shape} {wire.dtype}"
            )
        if self.weighted:
            self._evals += int(wire[W_WEIGHT].sum())
        self.raw_lines += raw_lines
        self.n_skipped += skipped
        pos = 0
        k = wire.shape[1]
        while pos < k:
            m = min(self.block_rows - self._fill, k - pos)
            self._buf[:, self._fill : self._fill + m] = wire[:, pos : pos + m]
            self._fill += m
            pos += m
            self.n_rows += m
            if self._fill == self.block_rows:
                self._f.write(self._buf.tobytes())
                self._fill = 0

    def add6(self, wire6: np.ndarray, raw_lines: int, skipped: int) -> None:
        """Append v6 rows (``[WIRE6_COLS, k]``; weighted: +weights row)
        to the spill section.

        Requires :meth:`begin6` to have reserved the v2 header (weighted
        files reserve it at construction).
        """
        if self._payload_at != HEADER6_BYTES:
            raise RuntimeError("call begin6() before the first add to write v6 rows")
        if wire6.dtype != np.uint32 or wire6.shape[0] != self._cols6:
            raise ValueError(
                f"expected [{self._cols6}, k] uint32, got {wire6.shape} {wire6.dtype}"
            )
        if self.weighted:
            self._evals += int(wire6[W6_WEIGHT].sum())
        if self._f6 is None:
            self._f6 = open(self._path + ".spill6", "wb")
            self._buf6 = np.empty((self._cols6, self.block_rows), dtype=np.uint32)
        self.raw_lines += raw_lines
        self.n_skipped += skipped
        pos = 0
        k = wire6.shape[1]
        while pos < k:
            m = min(self.block_rows - self._fill6, k - pos)
            self._buf6[:, self._fill6:self._fill6 + m] = wire6[:, pos:pos + m]
            self._fill6 += m
            pos += m
            self.n6_rows += m
            if self._fill6 == self.block_rows:
                self._f6.write(self._buf6.tobytes())
                self._fill6 = 0

    def close(self) -> None:
        if self._f.closed:
            return
        if self._fill:
            self._f.write(np.ascontiguousarray(self._buf[:, : self._fill]).tobytes())
            self._fill = 0
        if self._f6 is not None:
            # append the v6 section after the last v4 block
            if self._fill6:
                self._f6.write(
                    np.ascontiguousarray(self._buf6[:, : self._fill6]).tobytes()
                )
                self._fill6 = 0
            self._f6.flush()
            self._f6.close()
            with open(self._path + ".spill6", "rb") as sf:
                while True:
                    chunk = sf.read(1 << 22)
                    if not chunk:
                        break
                    self._f.write(chunk)
            os.unlink(self._path + ".spill6")
            self._f6 = None
        self._f.flush()
        self._f.seek(0)
        self._f.write(self._header(final=True))
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()

    def abort(self) -> None:
        """Stop without finalizing: the partial-magic header stays, so the
        file is refused by every reader rather than read short."""
        if not self._f.closed:
            self._f.close()
        if self._f6 is not None:
            self._f6.close()
            try:
                os.unlink(self._path + ".spill6")
            except OSError:
                pass
            self._f6 = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        else:
            self.close()


def is_wire_file(path: str) -> bool:
    """True if ``path`` is a wire file — complete OR partial (cheap sniff).

    Partial files (crashed converts) must count here: routing decides
    between the text parser and :class:`WireReader`, and a partial file
    fed to the text parser would silently skip every binary "line" and
    report a clean empty analysis.  Routing it to WireReader instead
    surfaces the loud "incomplete wire file" refusal.
    """
    try:
        with open(path, "rb") as f:
            head = f.read(len(MAGIC))
            return head in (MAGIC, MAGIC6, MAGIC_W, MAGIC_PARTIAL)
    except OSError:
        return False


class _WireFile:
    """One mmap'd wire file, header-validated.

    The open + header read + mmap establishment is the wire path's IO
    seam: it runs under the central ``wire.read`` retry policy (callers
    construct through :func:`_open_wire_file`), so a transient storage
    hiccup at open time re-attempts instead of aborting a resumable run.
    Typed refusals (bad magic, truncation, fingerprint mismatch) are
    permanent and escalate unchanged.
    """

    def __init__(self, path: str, fp: bytes | None):
        from ..runtime import faults

        faults.fire("stream.wire.read.fail")
        self.path = path
        f = open(path, "rb")
        try:
            head = f.read(HEADER6_BYTES)
            if len(head) >= len(MAGIC_PARTIAL) and head.startswith(MAGIC_PARTIAL):
                raise WireFormatError(
                    f"{path!r} is an incomplete wire file (the convert that "
                    "wrote it crashed or was aborted); re-run the convert"
                )
            self.weighted = head.startswith(MAGIC_W)
            if head.startswith(MAGIC6) or self.weighted:
                if len(head) < HEADER6_BYTES:
                    raise WireFormatError(
                        f"{path!r} is not a wire file (bad magic/header)"
                    )
                (_, self.block_rows, _r, self.n_rows, self.n6_rows,
                 self.raw_lines, self.n_evals, self.n_skipped,
                 self.fp) = struct.unpack(_HEADER6_FMT, head)
                self._payload_at = HEADER6_BYTES
            elif head.startswith(MAGIC):
                if len(head) < HEADER_BYTES:
                    raise WireFormatError(
                        f"{path!r} is not a wire file (bad magic/header)"
                    )
                (_, self.block_rows, _r, self.n_rows, self.raw_lines,
                 self.n_evals, self.n_skipped, self.fp) = struct.unpack(
                    _HEADER_FMT, head[:HEADER_BYTES]
                )
                self.n6_rows = 0
                self._payload_at = HEADER_BYTES
            else:
                raise WireFormatError(f"{path!r} is not a wire file (bad magic/header)")
            if self.block_rows < 1:
                raise WireFormatError(
                    f"{path!r} has a corrupt header (block_rows == 0)"
                )
            if fp is not None and self.fp != fp:
                raise WireFormatError(
                    f"{path!r} was converted against a different ruleset "
                    "(fingerprint mismatch); re-run `ruleset-analyze convert` "
                    "with the current packed ruleset"
                )
            self.cols4 = WIREW_COLS if self.weighted else WIRE_COLS
            self.cols6 = WIRE6W_COLS if self.weighted else WIRE6_COLS
            self._row_bytes = ROWW_BYTES if self.weighted else ROW_BYTES
            self._row6_bytes = ROW6W_BYTES if self.weighted else ROW6_BYTES
            self._v6_at = self._payload_at + self.n_rows * self._row_bytes
            need = self._v6_at + self.n6_rows * self._row6_bytes
            size = os.fstat(f.fileno()).st_size
            if size < need:
                raise WireFormatError(
                    f"{path!r} is truncated: header claims "
                    f"{self.n_rows}+{self.n6_rows} rows ({need} bytes) but "
                    f"the file has {size}"
                )
            if self.n_rows or self.n6_rows:
                self._mm = mmap.mmap(f.fileno(), need, access=mmap.ACCESS_READ)
            else:
                self._mm = None
        finally:
            f.close()  # mmap keeps its own reference

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # A zero-copy block() view is still alive somewhere (e.g.
                # a chunk-loop frame kept reachable by an in-flight
                # exception traceback).  mmap refuses to close under live
                # exports; dropping our reference lets GC finalize the
                # mapping once the last view dies — and close() must not
                # replace the caller's real exception with a BufferError.
                pass
            self._mm = None

    def block(self, b: int) -> np.ndarray:
        """Read-only [cols4, r] view of payload block ``b`` (cols4 is
        WIRE_COLS, or WIREW_COLS for weighted v3 files)."""
        start = b * self.block_rows
        r = min(self.block_rows, self.n_rows - start)
        off = self._payload_at + start * self._row_bytes
        arr = np.frombuffer(self._mm, dtype=np.uint32, count=self.cols4 * r, offset=off)
        return arr.reshape(self.cols4, r)

    def block6(self, b: int) -> np.ndarray:
        """Read-only [cols6, r] view of v6-section block ``b``."""
        start = b * self.block_rows
        r = min(self.block_rows, self.n6_rows - start)
        off = self._v6_at + start * self._row6_bytes
        arr = np.frombuffer(
            self._mm, dtype=np.uint32, count=self.cols6 * r, offset=off
        )
        return arr.reshape(self.cols6, r)

    @property
    def n_blocks(self) -> int:
        return (self.n_rows + self.block_rows - 1) // self.block_rows if self.n_rows else 0

    @property
    def n6_blocks(self) -> int:
        return (
            (self.n6_rows + self.block_rows - 1) // self.block_rows
            if self.n6_rows
            else 0
        )


def _open_wire_file(path: str, fp: bytes | None) -> "_WireFile":
    """Construct one _WireFile under the ``wire.read`` retry policy."""
    from ..runtime import retrypolicy

    return retrypolicy.call("wire.read", lambda: _WireFile(path, fp))


class WireReader:
    """mmap-backed batch source over one or more wire files.

    ``iter_batches`` re-chunks rows to exactly ``batch_size`` columns.
    When a request lines up with a stored block (the common case: default
    block_rows == default batch size and no mid-block resume offset), the
    yielded array is a zero-copy read-only mmap view — ``device_put``
    consumes it directly with no host-side copy or transpose.
    """

    def __init__(
        self,
        paths: list[str],
        packed: PackedRuleset | None = None,
        fingerprint: bytes | None = None,
    ):
        """``packed`` validates each file's ruleset fingerprint; callers
        inspecting many files can hash once themselves and pass
        ``fingerprint`` instead."""
        fp = fingerprint
        if fp is None and packed is not None:
            fp = ruleset_fingerprint(packed)
        self._files = [_open_wire_file(p, fp) for p in paths]
        kinds = {f.weighted for f in self._files}
        if len(kinds) > 1:
            for f in self._files:
                f.close()
            raise WireFormatError(
                "cannot mix weighted (RAWIREv3) and plain wire files in "
                "one input list; re-convert for a uniform set"
            )
        #: True when every file stores coalesced (weighted) rows
        self.weighted = bool(kinds.pop()) if kinds else False
        self._cols4 = WIREW_COLS if self.weighted else WIRE_COLS
        self._cols6 = WIRE6W_COLS if self.weighted else WIRE6_COLS
        blocks = {f.block_rows for f in self._files}
        #: Common payload block size, or 0 when the files disagree (the
        #: reader handles mixed blocks fine; only the aggregate is
        #: meaningless then).
        self.block_rows = blocks.pop() if len(blocks) == 1 else 0
        self.n_rows = sum(f.n_rows for f in self._files)
        self.n6_rows = sum(f.n6_rows for f in self._files)
        self.raw_lines = sum(f.raw_lines for f in self._files)
        self.n_evals = sum(f.n_evals for f in self._files)
        self.n_skipped = sum(f.n_skipped for f in self._files)

    def close(self) -> None:
        for f in self._files:
            f.close()

    def iter_batches(
        self, skip_rows: int, batch_size: int
    ) -> Iterator[tuple[np.ndarray, int]]:
        """Yield ``([WIRE_COLS, batch_size] uint32, rows_in_batch)``.

        The final partial batch is zero-padded to ``batch_size`` columns
        (zero meta == valid bit clear, so padding is masked on device).
        Raises ResumeInputMismatch if the files hold fewer than
        ``skip_rows`` rows.
        """
        if skip_rows > self.n_rows:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {skip_rows} rows but the wire input has "
                f"only {self.n_rows}; wrong or truncated input"
            )
        pend: np.ndarray | None = None  # partially filled output batch
        fill = 0
        to_skip = skip_rows
        for wf in self._files:
            if to_skip >= wf.n_rows:
                to_skip -= wf.n_rows
                continue
            b0 = to_skip // wf.block_rows if wf.block_rows else 0
            to_skip -= b0 * wf.block_rows  # rows in the blocks jumped over
            for b in range(b0, wf.n_blocks):
                blk = wf.block(b)
                if to_skip:
                    drop = min(to_skip, blk.shape[1])
                    blk = blk[:, drop:]
                    to_skip -= drop
                    if not blk.shape[1]:
                        continue
                pos = 0
                n = blk.shape[1]
                # zero-copy fast path: a full block, nothing pending
                if fill == 0 and n == batch_size:
                    yield blk, n
                    continue
                while pos < n:
                    if pend is None:
                        pend = np.zeros((self._cols4, batch_size), dtype=np.uint32)
                    m = min(batch_size - fill, n - pos)
                    pend[:, fill : fill + m] = blk[:, pos : pos + m]
                    fill += m
                    pos += m
                    if fill == batch_size:
                        yield pend, fill
                        pend = None
                        fill = 0
        if fill:
            yield pend, fill

    def iter_batches6(
        self, skip_rows: int, batch_size: int
    ) -> Iterator[tuple[np.ndarray, int]]:
        """Yield ``([WIRE6_COLS, batch_size] uint32, rows_in_batch)``.

        The v6 sections of every file, concatenated — consumed AFTER the
        v4 stream (drivers run the two phases in that fixed order, so
        resume offsets over the concatenated v4-then-v6 row stream are
        deterministic).  Padding and zero-copy behavior mirror
        :meth:`iter_batches`.
        """
        if skip_rows > self.n6_rows:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {skip_rows} v6 rows but the wire input "
                f"has only {self.n6_rows}; wrong or truncated input"
            )
        pend: np.ndarray | None = None
        fill = 0
        to_skip = skip_rows
        for wf in self._files:
            if to_skip >= wf.n6_rows:
                to_skip -= wf.n6_rows
                continue
            b0 = to_skip // wf.block_rows if wf.block_rows else 0
            to_skip -= b0 * wf.block_rows
            for b in range(b0, wf.n6_blocks):
                blk = wf.block6(b)
                if to_skip:
                    drop = min(to_skip, blk.shape[1])
                    blk = blk[:, drop:]
                    to_skip -= drop
                    if not blk.shape[1]:
                        continue
                pos = 0
                n = blk.shape[1]
                if fill == 0 and n == batch_size:
                    yield blk, n
                    continue
                while pos < n:
                    if pend is None:
                        pend = np.zeros((self._cols6, batch_size), dtype=np.uint32)
                    m = min(batch_size - fill, n - pos)
                    pend[:, fill:fill + m] = blk[:, pos:pos + m]
                    fill += m
                    pos += m
                    if fill == batch_size:
                        yield pend, fill
                        pend = None
                        fill = 0
        if fill:
            yield pend, fill


def convert_logs(
    packed: PackedRuleset,
    log_paths: list[str],
    out_path: str,
    *,
    native: bool | None = None,
    batch_size: int = DEFAULT_BLOCK_ROWS,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    feed_workers: int = 0,
    coalesce: bool = False,
) -> dict:
    """Parse text syslog once and write a ``.rawire`` file; return stats.

    Uses the same batch sources as the run path (native C++ parser when
    available, pure-Python fallback, or the multi-process feeder with
    ``feed_workers > 1``), so the row sequence written is exactly the
    sequence a text run would feed the device — the output file is
    byte-identical across all three parse tiers (chunk boundaries differ
    between tiers, but the file stores only the row stream).

    ``coalesce=True`` writes the weighted v3 format: each per-batch run
    of duplicate evaluation tuples stores ONCE with its repetition count
    (ISSUE 5).  Registers from a weighted run are bit-identical to the
    plain file's (weight-linear/idempotent updates); the file shrinks by
    ~the corpus's compaction ratio at 20 B/row, and so does every
    downstream device step.
    """
    from . import fastparse

    if feed_workers and feed_workers > 1:
        if native is False:
            raise ValueError(
                "feed_workers requires the native parser; drop native=False"
            )
        from .feeder import ParallelFeeder

        src = ParallelFeeder(packed, log_paths, n_workers=feed_workers)
        packer = src.packer
        batches = src.batches(0, batch_size)
        take_v6 = src.take_v6 if packed.has_v6 else None
        parser_name = f"native-feeder-x{feed_workers}"
    else:
        use_native = native if native is not None else fastparse.available()
        if use_native:
            packer = fastparse.NativePacker(packed)
            batches = fastparse.batches_from_files(log_paths, packer, batch_size)
            take_v6 = packer.take_v6 if packed.has_v6 else None
        else:
            from ..runtime.stream import _iter_files, _TextSource

            text_src = _TextSource(packed, _iter_files(log_paths))
            packer = text_src.packer
            batches = text_src.batches(0, batch_size)
            take_v6 = text_src.take_v6 if packed.has_v6 else None
        parser_name = "native" if use_native else "python"

    last_skipped = 0
    with WireWriter(
        out_path, ruleset_fingerprint(packed), block_rows, weighted=coalesce
    ) as w:
        if packed.has_v6:
            w.begin6()
        for batch, n_raw in batches:
            skipped = packer.skipped
            # keep only evaluation rows, wherever the source put them
            # (every current source packs them densely from column 0, but
            # the mask keeps this correct for any conforming source).
            # The text source marks a zero-v4-row batch as None (a
            # mostly-v6/unparseable stretch): no v4 rows to store, but
            # its raw-line/skip accounting must still land in the header.
            valid = (
                np.zeros((TUPLE_COLS, 0), dtype=np.uint32)
                if batch is None
                else batch[:, batch[T_VALID] == 1]
            )
            wire4 = compact_batch(valid)
            if coalesce:
                wire4 = coalesce_wire(wire4)
            w.add(wire4, n_raw, skipped - last_skipped)
            last_skipped = skipped
            if take_v6 is not None:
                rows6 = take_v6()
                if len(rows6):
                    t6 = np.asarray(rows6, dtype=np.uint32).T
                    wire6 = compact_batch6(t6)
                    if coalesce:
                        wire6 = coalesce_wire6(wire6)
                    w.add6(wire6, 0, 0)
    return {
        "rows": w.n_rows,
        "rows6": w.n6_rows,
        "raw_lines": w.raw_lines,
        "evals": w._evals if coalesce else w.n_rows + w.n6_rows,
        "skipped": w.n_skipped,
        "bytes": os.path.getsize(out_path),
        "parser": parser_name,
        "weighted": coalesce,
    }


def sanity_check_valid_bits(wire: np.ndarray) -> tuple[int, int]:
    """(valid, invalid) row counts of a wire batch (meta bit 23)."""
    v = int(np.count_nonzero(wire[W_META] & np.uint32(1 << 23)))
    return v, wire.shape[1] - v
