"""On-disk wire format: pre-tokenized syslog, 16 bytes/line, mmap-readable.

SURVEY.md §8.2 names host regex parse as the end-to-end bottleneck and
prescribes a "pre-tokenized binary input format for the benchmark path".
This module makes that format a production tier, not a bench-only hack:

- ``ruleset-analyze convert`` parses text syslog ONCE (native C++ parser
  when available) and writes a ``.rawire`` file holding each ACL
  evaluation as the same 4-word bit-packed row that crosses the
  host->device link (``pack.compact_batch``: src | dst | sport<<16|dport |
  proto<<24|valid<<23|acl).  Re-running an analysis then skips the parse
  entirely — the mmap-backed reader feeds the device step at memory
  bandwidth, which is what lets a small host keep a TPU busy.

- The file is bound to the ruleset it was packed against: ACL gids are
  ruleset-relative, so the header carries a ruleset fingerprint and the
  reader refuses a mismatched ruleset instead of silently attributing
  hits to the wrong ACLs.

Layout (all little-endian):

  header, 64 bytes:
    0   magic     8s   b"RAWIREv1"
    8   block_rows u32  rows per payload block
    12  reserved  u32
    16  n_rows    u64  total evaluation rows in the payload
    24  raw_lines u64  raw text lines the converter consumed
    32  n_evals   u64  evaluations emitted (== n_rows)
    40  n_skipped u64  raw lines that produced no evaluation
    48  fp        16s  ruleset fingerprint (sha256 prefix)
  payload: ceil(n_rows / block_rows) blocks; block b holds
    r = min(block_rows, n_rows - b*block_rows) rows stored column-major
    as a C-contiguous [WIRE_COLS, r] uint32 plane — so a whole block is a
    zero-copy mmap slice ready for jax.device_put.

Only evaluation rows are stored (a skipped line would be 16 zero bytes of
padding the device masks out anyway); the header keeps the raw-line
accounting so reports state true input totals.  Rows appear in exactly
the order the text path would evaluate them, so registers and per-rule
counts from a ``.rawire`` run are bit-identical to the text run.
"""

from __future__ import annotations

import hashlib
import mmap
import os
import struct
from collections.abc import Iterator

import numpy as np

from ..errors import AnalysisError
from .pack import (
    T_VALID,
    TUPLE_COLS,
    W_META,
    WIRE_COLS,
    PackedRuleset,
    compact_batch,
)

MAGIC = b"RAWIREv1"
#: Placeholder magic while a convert is in flight; only a successful
#: ``WireWriter.close()`` upgrades it to MAGIC, so a crashed or aborted
#: convert leaves a file every reader refuses ("not a wire file") instead
#: of a silently short one.
MAGIC_PARTIAL = b"RAWIRE??"
HEADER_BYTES = 64
_HEADER_FMT = "<8sII4Q16s"
#: Default rows per payload block.  Matches the default run batch size so
#: the aligned read path hands mmap views straight to device_put.
DEFAULT_BLOCK_ROWS = 1 << 16

ROW_BYTES = WIRE_COLS * 4  # 16 B/line


def ruleset_fingerprint(packed: PackedRuleset) -> bytes:
    """16-byte identity of the gid universe a wire file is valid for.

    Covers everything that maps a log line to (acl gid, key): the expanded
    rule matrix, deny keys, ACL gid assignment, and interface bindings.
    """
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(packed.rules).tobytes())
    h.update(np.ascontiguousarray(packed.deny_key).tobytes())
    for (fw, acl), gid in sorted(packed.acl_gid.items()):
        h.update(f"a:{fw}/{acl}={gid};".encode())
    for (fw, iface), gid in sorted(packed.bindings.items()):
        h.update(f"i:{fw}/{iface}={gid};".encode())
    for (fw, iface), gid in sorted(packed.bindings_out.items()):
        h.update(f"o:{fw}/{iface}={gid};".encode())
    return h.digest()[:16]


class WireFormatError(AnalysisError):
    """Bad magic, truncated payload, or ruleset mismatch."""


class WireWriter:
    """Stream evaluation rows into a ``.rawire`` file.

    Feed dense wire-format column batches (``[WIRE_COLS, k]`` uint32, all
    rows valid); blocks are written as they fill and the header is
    back-patched on close.  Until :meth:`close` succeeds the header
    carries ``MAGIC_PARTIAL``, so a convert that crashes, is interrupted,
    or calls :meth:`abort` leaves a file every reader refuses outright —
    never one that validates with only part of the rows.
    """

    def __init__(
        self,
        path: str,
        fp: bytes,
        block_rows: int = DEFAULT_BLOCK_ROWS,
    ):
        if block_rows <= 0:
            raise ValueError("block_rows must be positive")
        self._f = open(path, "wb")
        self._fp = fp
        self.block_rows = block_rows
        self.n_rows = 0
        self.raw_lines = 0
        self.n_skipped = 0
        self._buf = np.empty((WIRE_COLS, block_rows), dtype=np.uint32)
        self._fill = 0
        # placeholder header; rewritten with the final magic + counts on close
        self._f.write(self._header(final=False))

    def _header(self, final: bool = True) -> bytes:
        return struct.pack(
            _HEADER_FMT,
            MAGIC if final else MAGIC_PARTIAL,
            self.block_rows,
            0,
            self.n_rows,
            self.raw_lines,
            self.n_rows,  # n_evals == stored rows
            self.n_skipped,
            self._fp,
        )

    def add(self, wire: np.ndarray, raw_lines: int, skipped: int) -> None:
        """Append ``wire[:, :k]`` rows covering ``raw_lines`` text lines."""
        if wire.dtype != np.uint32 or wire.shape[0] != WIRE_COLS:
            raise ValueError(f"expected [WIRE_COLS, k] uint32, got {wire.shape} {wire.dtype}")
        self.raw_lines += raw_lines
        self.n_skipped += skipped
        pos = 0
        k = wire.shape[1]
        while pos < k:
            m = min(self.block_rows - self._fill, k - pos)
            self._buf[:, self._fill : self._fill + m] = wire[:, pos : pos + m]
            self._fill += m
            pos += m
            self.n_rows += m
            if self._fill == self.block_rows:
                self._f.write(self._buf.tobytes())
                self._fill = 0

    def close(self) -> None:
        if self._f.closed:
            return
        if self._fill:
            self._f.write(np.ascontiguousarray(self._buf[:, : self._fill]).tobytes())
            self._fill = 0
        self._f.flush()
        self._f.seek(0)
        self._f.write(self._header(final=True))
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()

    def abort(self) -> None:
        """Stop without finalizing: the partial-magic header stays, so the
        file is refused by every reader rather than read short."""
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        else:
            self.close()


def is_wire_file(path: str) -> bool:
    """True if ``path`` is a wire file — complete OR partial (cheap sniff).

    Partial files (crashed converts) must count here: routing decides
    between the text parser and :class:`WireReader`, and a partial file
    fed to the text parser would silently skip every binary "line" and
    report a clean empty analysis.  Routing it to WireReader instead
    surfaces the loud "incomplete wire file" refusal.
    """
    try:
        with open(path, "rb") as f:
            head = f.read(len(MAGIC))
            return head == MAGIC or head == MAGIC_PARTIAL
    except OSError:
        return False


class _WireFile:
    """One mmap'd wire file, header-validated."""

    def __init__(self, path: str, fp: bytes | None):
        self.path = path
        f = open(path, "rb")
        try:
            head = f.read(HEADER_BYTES)
            if len(head) >= len(MAGIC_PARTIAL) and head.startswith(MAGIC_PARTIAL):
                raise WireFormatError(
                    f"{path!r} is an incomplete wire file (the convert that "
                    "wrote it crashed or was aborted); re-run the convert"
                )
            if len(head) < HEADER_BYTES or not head.startswith(MAGIC):
                raise WireFormatError(f"{path!r} is not a wire file (bad magic/header)")
            (_, self.block_rows, _r, self.n_rows, self.raw_lines,
             self.n_evals, self.n_skipped, self.fp) = struct.unpack(_HEADER_FMT, head)
            if self.block_rows < 1:
                raise WireFormatError(
                    f"{path!r} has a corrupt header (block_rows == 0)"
                )
            if fp is not None and self.fp != fp:
                raise WireFormatError(
                    f"{path!r} was converted against a different ruleset "
                    "(fingerprint mismatch); re-run `ruleset-analyze convert` "
                    "with the current packed ruleset"
                )
            need = HEADER_BYTES + self.n_rows * ROW_BYTES
            size = os.fstat(f.fileno()).st_size
            if size < need:
                raise WireFormatError(
                    f"{path!r} is truncated: header claims {self.n_rows} rows "
                    f"({need} bytes) but the file has {size}"
                )
            if self.n_rows:
                self._mm = mmap.mmap(f.fileno(), need, access=mmap.ACCESS_READ)
            else:
                self._mm = None
        finally:
            f.close()  # mmap keeps its own reference

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # A zero-copy block() view is still alive somewhere (e.g.
                # a chunk-loop frame kept reachable by an in-flight
                # exception traceback).  mmap refuses to close under live
                # exports; dropping our reference lets GC finalize the
                # mapping once the last view dies — and close() must not
                # replace the caller's real exception with a BufferError.
                pass
            self._mm = None

    def block(self, b: int) -> np.ndarray:
        """Read-only [WIRE_COLS, r] view of payload block ``b``."""
        start = b * self.block_rows
        r = min(self.block_rows, self.n_rows - start)
        off = HEADER_BYTES + start * ROW_BYTES
        arr = np.frombuffer(self._mm, dtype=np.uint32, count=WIRE_COLS * r, offset=off)
        return arr.reshape(WIRE_COLS, r)

    @property
    def n_blocks(self) -> int:
        return (self.n_rows + self.block_rows - 1) // self.block_rows if self.n_rows else 0


class WireReader:
    """mmap-backed batch source over one or more wire files.

    ``iter_batches`` re-chunks rows to exactly ``batch_size`` columns.
    When a request lines up with a stored block (the common case: default
    block_rows == default batch size and no mid-block resume offset), the
    yielded array is a zero-copy read-only mmap view — ``device_put``
    consumes it directly with no host-side copy or transpose.
    """

    def __init__(
        self,
        paths: list[str],
        packed: PackedRuleset | None = None,
        fingerprint: bytes | None = None,
    ):
        """``packed`` validates each file's ruleset fingerprint; callers
        inspecting many files can hash once themselves and pass
        ``fingerprint`` instead."""
        fp = fingerprint
        if fp is None and packed is not None:
            fp = ruleset_fingerprint(packed)
        self._files = [_WireFile(p, fp) for p in paths]
        blocks = {f.block_rows for f in self._files}
        #: Common payload block size, or 0 when the files disagree (the
        #: reader handles mixed blocks fine; only the aggregate is
        #: meaningless then).
        self.block_rows = blocks.pop() if len(blocks) == 1 else 0
        self.n_rows = sum(f.n_rows for f in self._files)
        self.raw_lines = sum(f.raw_lines for f in self._files)
        self.n_evals = sum(f.n_evals for f in self._files)
        self.n_skipped = sum(f.n_skipped for f in self._files)

    def close(self) -> None:
        for f in self._files:
            f.close()

    def iter_batches(
        self, skip_rows: int, batch_size: int
    ) -> Iterator[tuple[np.ndarray, int]]:
        """Yield ``([WIRE_COLS, batch_size] uint32, rows_in_batch)``.

        The final partial batch is zero-padded to ``batch_size`` columns
        (zero meta == valid bit clear, so padding is masked on device).
        Raises ResumeInputMismatch if the files hold fewer than
        ``skip_rows`` rows.
        """
        if skip_rows > self.n_rows:
            from ..errors import ResumeInputMismatch

            raise ResumeInputMismatch(
                f"snapshot consumed {skip_rows} rows but the wire input has "
                f"only {self.n_rows}; wrong or truncated input"
            )
        pend: np.ndarray | None = None  # partially filled output batch
        fill = 0
        to_skip = skip_rows
        for wf in self._files:
            if to_skip >= wf.n_rows:
                to_skip -= wf.n_rows
                continue
            b0 = to_skip // wf.block_rows if wf.block_rows else 0
            to_skip -= b0 * wf.block_rows  # rows in the blocks jumped over
            for b in range(b0, wf.n_blocks):
                blk = wf.block(b)
                if to_skip:
                    drop = min(to_skip, blk.shape[1])
                    blk = blk[:, drop:]
                    to_skip -= drop
                    if not blk.shape[1]:
                        continue
                pos = 0
                n = blk.shape[1]
                # zero-copy fast path: a full block, nothing pending
                if fill == 0 and n == batch_size:
                    yield blk, n
                    continue
                while pos < n:
                    if pend is None:
                        pend = np.zeros((WIRE_COLS, batch_size), dtype=np.uint32)
                    m = min(batch_size - fill, n - pos)
                    pend[:, fill : fill + m] = blk[:, pos : pos + m]
                    fill += m
                    pos += m
                    if fill == batch_size:
                        yield pend, fill
                        pend = None
                        fill = 0
        if fill:
            yield pend, fill


def convert_logs(
    packed: PackedRuleset,
    log_paths: list[str],
    out_path: str,
    *,
    native: bool | None = None,
    batch_size: int = DEFAULT_BLOCK_ROWS,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    feed_workers: int = 0,
) -> dict:
    """Parse text syslog once and write a ``.rawire`` file; return stats.

    Uses the same batch sources as the run path (native C++ parser when
    available, pure-Python fallback, or the multi-process feeder with
    ``feed_workers > 1``), so the row sequence written is exactly the
    sequence a text run would feed the device — the output file is
    byte-identical across all three parse tiers (chunk boundaries differ
    between tiers, but the file stores only the row stream).
    """
    from . import fastparse

    if feed_workers and feed_workers > 1:
        if native is False:
            raise ValueError(
                "feed_workers requires the native parser; drop native=False"
            )
        from .feeder import ParallelFeeder

        src = ParallelFeeder(packed, log_paths, n_workers=feed_workers)
        packer = src.packer
        batches = src.batches(0, batch_size)
        parser_name = f"native-feeder-x{feed_workers}"
    else:
        use_native = native if native is not None else fastparse.available()
        if use_native:
            packer = fastparse.NativePacker(packed)
            batches = fastparse.batches_from_files(log_paths, packer, batch_size)
        else:
            from ..runtime.stream import _iter_files, _TextSource

            src = _TextSource(packed, _iter_files(log_paths))
            packer = src.packer
            batches = src.batches(0, batch_size)
        parser_name = "native" if use_native else "python"

    last_skipped = 0
    with WireWriter(out_path, ruleset_fingerprint(packed), block_rows) as w:
        for batch, n_raw in batches:
            skipped = packer.skipped
            # keep only evaluation rows, wherever the source put them
            # (every current source packs them densely from column 0, but
            # the mask keeps this correct for any conforming source)
            valid = batch[:, batch[T_VALID] == 1]
            w.add(compact_batch(valid), n_raw, skipped - last_skipped)
            last_skipped = skipped
    return {
        "rows": w.n_rows,
        "raw_lines": w.raw_lines,
        "evals": w.n_rows,
        "skipped": w.n_skipped,
        "bytes": os.path.getsize(out_path),
        "parser": parser_name,
    }


def sanity_check_valid_bits(wire: np.ndarray) -> tuple[int, int]:
    """(valid, invalid) row counts of a wire batch (meta bit 23)."""
    v = int(np.count_nonzero(wire[W_META] & np.uint32(1 << 23)))
    return v, wire.shape[1] - v
