"""Pure-Python host layer: parsing, oracle semantics, synthetic data.

No JAX imports anywhere in this subpackage — it must stay importable and fast
on machines with no accelerator, exactly like the reference's host scripts.
"""
