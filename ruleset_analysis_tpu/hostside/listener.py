"""Live syslog listener tier for the always-on ``serve`` mode.

The batch tiers read finished files; a *service* has to take the traffic
as the network delivers it.  This module is the ingress edge of
``runtime/serve.py``: socket listeners (UDP datagrams and newline-framed
TCP — the two shapes real syslog relays speak) plus a rotating-file
tailer, all pushing decoded lines into one bounded :class:`LineQueue`.

Drop accounting is the load-bearing invariant (ROADMAP item 1): the
queue is bounded so a slow consumer exerts backpressure on *us*, never
unbounded memory — but a line that cannot be queued is **counted**, per
ingress, and the serve loop stamps every analysis window that overlaps a
drop (or a dead listener) with an explicit ``WindowIncomplete`` marker.
A dropped-line window is therefore never silently reported as zero-hit;
the report says "this window is missing N lines" instead (DESIGN §12).

Fault sites (runtime/faults.py): ``listener.drop`` forces one received
line to drop (exercising exactly that accounting), ``listener.stall``
wedges a listener thread mid-receive — the serve loop's liveness checks
and ``--stop-after`` bound must turn either into an explicit marker or a
typed abort, never a hang or a silent zero-hit window (tests/test_chaos).

Threads carry the ``ra-`` name prefix so the test harness's leak audit
covers them like every other pipeline thread.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque

from ..errors import AnalysisError
from ..runtime import faults, obs, retrypolicy


class LineQueue:
    """Bounded line queue with explicit, per-cause drop accounting.

    ``put`` never blocks the ingress thread: when the queue is full the
    line is dropped and counted (``dropped``).  Silently blocking a UDP
    receiver would just move the loss into the kernel socket buffer where
    nobody can count it — an explicit host-side counter is the only place
    the "never silently zero-hit" invariant can be enforced from.
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise AnalysisError(f"listener queue capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        #: (line, receipt time.monotonic()) pairs: the receipt stamp is
        #: where the serve tier's ingest->publish latency histogram
        #: starts its clock (DESIGN §20)
        self._q: deque[tuple[str, float]] = deque()
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self.received = 0  # lines handed to put() (drops included)
        self.dropped = 0  # lines put() could not queue
        self.forced_drops = 0  # listener.drop fault firings (subset of dropped)

    def put(self, line: str) -> bool:
        t = time.monotonic()
        with self._lock:
            self.received += 1
            if len(self._q) >= self.capacity:
                self.dropped += 1
                return False
            self._q.append((line, t))
            self._ready.notify()
            return True

    def note_forced_drop(self) -> None:
        """Account a line the ``listener.drop`` fault site discarded."""
        with self._lock:
            self.received += 1
            self.dropped += 1
            self.forced_drops += 1

    def note_discarded(self, n: int = 1) -> None:
        """Account ``n`` lines discarded before they could be queued
        (oversized unterminated frames)."""
        with self._lock:
            self.received += n
            self.dropped += n

    def discard_remaining(self) -> int:
        """Drop-and-count every queued line (bounded shutdown).

        A stop request must not analyze an unbounded backlog, but it
        must never pretend the backlog did not exist: the lines count as
        explicit drops so the final window carries the incomplete marker
        and ``summary.drops`` reports the loss.
        """
        with self._lock:
            n = len(self._q)
            self._q.clear()
            self.dropped += n
            return n

    def pop(self, timeout: float = 0.2) -> str | None:
        got = self.pop_ts(timeout)
        return got[0] if got is not None else None

    def pop_ts(self, timeout: float = 0.2) -> tuple[str, float] | None:
        """Next line WITH its receipt timestamp (``time.monotonic()``)."""
        with self._ready:
            if not self._q:
                self._ready.wait(timeout)
            if not self._q:
                return None
            return self._q.popleft()

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "depth": len(self._q),
                "received": self.received,
                "dropped": self.dropped,
                "forced_drops": self.forced_drops,
            }


# longest unterminated line a stream listener will buffer before
# discarding it as a counted drop: the bounded LineQueue is the module's
# memory guarantee, and a peer that never sends a newline must not be
# able to grow a side buffer past it (real syslog lines are < 8 KiB)
MAX_LINE_BYTES = 1 << 20


class BaseListener(threading.Thread):
    """One ingress thread feeding the shared queue.

    Lifecycle: ``start()`` -> receive loop -> ``close()`` (idempotent).
    A listener that dies on an unexpected error records it in ``.error``
    and sets ``.dead`` — the serve loop reads both and decides between
    "mark windows incomplete" and a typed abort.  An injected
    ``listener.stall`` parks the thread until shutdown (or the fault
    plan's disarm) releases it, then terminates it loudly — exactly a
    wedged receiver whose traffic is silently lost upstream.
    """

    kind = "base"

    def __init__(self, q: LineQueue, label: str):
        super().__init__(name=f"ra-listener-{label}", daemon=True)
        self.q = q
        self.label = label
        self.stop_event = threading.Event()
        self.dead = False
        self.error: BaseException | None = None
        #: liveness heartbeat: every receive-loop iteration (idle ones
        #: included) refreshes it, so a thread parked mid-push (injected
        #: listener.stall, frozen socket) is DETECTABLE — the serve loop
        #: compares beat age against the stall timeout instead of
        #: trusting is_alive(), which a wedged thread still satisfies
        self.beat = time.monotonic()

    # -- subclass surface ------------------------------------------------
    def _serve(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def _teardown(self) -> None:
        pass

    def _beat(self) -> None:
        """One receive-loop iteration tick: heartbeat + chaos seam.

        The ``listener.accept.fail`` site fires here so transient
        receive-loop faults are injectable in every listener kind; the
        ``listener.accept`` retry policy in :meth:`run` re-enters
        ``_serve`` on them.
        """
        self.beat = time.monotonic()
        faults.fire("listener.accept.fail")

    def _push_all(self, lines: list[str]) -> None:
        """Push a split batch; a fault mid-batch counts the unpushed
        remainder as explicit drops before propagating (the accept
        retry may resume this listener — no silent gap allowed)."""
        for i, line in enumerate(lines):
            try:
                self._push(line)
            except BaseException:
                rest = len(lines) - i - 1
                if rest and not self.stop_event.is_set():
                    self.q.note_discarded(rest)
                raise

    # -- shared line path ------------------------------------------------
    def _push(self, line: str) -> None:
        """Fault-instrumented push: the ONLY way lines enter the queue.

        A fault that escapes mid-push (a released ``listener.stall``, a
        transient burst the accept retry will re-enter around) counts
        its in-flight line as an explicit drop BEFORE propagating — the
        retry policy may resume this listener, and the resumed stream
        must never contain a silent gap.
        """
        try:
            faults.fire("listener.stall", stop=self.stop_event)
            line = faults.fire(
                "listener.drop", payload=line, corrupt=lambda _p, _rng: None
            )
        except BaseException:
            if not self.stop_event.is_set():
                self.q.note_discarded()
                obs.instant(
                    "listener.drop",
                    args={"listener": self.label, "cause": "fault"},
                )
            raise
        if line is None:
            # the site ate the line: account it as an explicit drop so the
            # window it belonged to reports incomplete, never zero-hit
            self.q.note_forced_drop()
            obs.instant("listener.drop", args={"listener": self.label})
            return
        if not self.q.put(line):
            obs.instant("listener.drop", args={"listener": self.label})

    def run(self) -> None:
        try:
            # the receive loop runs under the listener.accept retry
            # policy: a transient fault (classified by errors.is_transient
            # — an injected listener.accept.fail burst, a recoverable
            # socket error) re-enters _serve with seeded backoff instead
            # of killing the listener; exhaustion or a permanent error
            # records it and marks the listener dead — the serve loop's
            # existing escalation (windows incomplete; all-dead aborts
            # typed) takes over from there
            retrypolicy.call("listener.accept", self._serve, stop=self.stop_event)
        except BaseException as e:  # recorded, surfaced by the serve loop
            if not self.stop_event.is_set():
                self.error = e
        finally:
            self.dead = True
            self._teardown()

    def close(self) -> None:
        self.stop_event.set()
        self._teardown()
        if self.ident is not None:  # join() on a never-started thread raises
            self.join(timeout=10.0)


def _bind_retry(sock_type: int, host: str, port: int, finish):
    """Create + bind one socket under the ``listener.bind`` retry policy.

    EADDRINUSE — the TIME_WAIT rebind after a service restart — is the
    canonical transient here; the policy waits it out with seeded
    backoff.  A permanent refusal (EACCES on a privileged port) or an
    exhausted budget escalates the original OSError, which the CLI's
    construction handler reports as the documented clean bind error.
    ``finish`` applies kind-specific setup (listen()) before the socket
    is returned; a failed attempt always closes its socket.
    """

    def _attempt():
        faults.fire("listener.bind.fail")
        s = socket.socket(socket.AF_INET, sock_type)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, port))
            return finish(s)
        except BaseException:
            s.close()
            raise

    return retrypolicy.call("listener.bind", _attempt)


class UdpSyslogListener(BaseListener):
    """RFC3164-style UDP syslog: one datagram = one log line."""

    kind = "udp"

    def __init__(self, q: LineQueue, host: str, port: int):
        super().__init__(q, f"udp-{host}-{port}")
        self._sock = _bind_retry(
            socket.SOCK_DGRAM, host, port, lambda s: s
        )
        self._sock.settimeout(0.2)
        self.address = self._sock.getsockname()

    def _serve(self) -> None:
        while not self.stop_event.is_set():
            self._beat()
            try:
                data, _addr = self._sock.recvfrom(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                if self.stop_event.is_set():
                    return
                raise
            # one datagram, one message (trailing newline tolerated)
            self._push(data.decode("utf-8", errors="replace").rstrip("\r\n"))

    def _teardown(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class TcpSyslogListener(BaseListener):
    """Newline-framed TCP syslog (the reliable-transport relay shape).

    Single accept loop with short socket timeouts — syslog relays hold
    few long-lived connections, so a select fleet would be overkill; a
    dead peer is detected at the next read.
    """

    kind = "tcp"

    def __init__(self, q: LineQueue, host: str, port: int):
        super().__init__(q, f"tcp-{host}-{port}")
        self._sock = _bind_retry(
            socket.SOCK_STREAM, host, port, lambda s: (s.listen(8), s)[1]
        )
        self._sock.settimeout(0.2)
        self.address = self._sock.getsockname()
        self._conns: list[socket.socket] = []

    def _serve(self) -> None:
        import selectors

        sel = selectors.DefaultSelector()
        sel.register(self._sock, selectors.EVENT_READ, ("accept", None))
        # partial-frame buffers persist on the instance: a transient
        # receive-loop fault re-enters _serve (listener.accept retry) and
        # must neither drop established connections nor lose their
        # buffered half-lines
        bufs: dict[socket.socket, bytes] = getattr(self, "_bufs", {})
        self._bufs = bufs
        skipping: set[socket.socket] = getattr(self, "_skipping", set())
        self._skipping = skipping
        for conn in self._conns:
            try:
                sel.register(conn, selectors.EVENT_READ, ("conn", None))
                bufs.setdefault(conn, b"")
            except (ValueError, OSError):
                pass  # closed mid-retry; the next recv path cleans up
        try:
            while not self.stop_event.is_set():
                self._beat()
                for key, _ev in sel.select(timeout=0.2):
                    tag, _ = key.data
                    if tag == "accept":
                        try:
                            conn, _addr = self._sock.accept()
                        except OSError:
                            continue
                        conn.setblocking(False)
                        self._conns.append(conn)
                        bufs[conn] = b""
                        sel.register(conn, selectors.EVENT_READ, ("conn", None))
                        continue
                    conn = key.fileobj
                    try:
                        data = conn.recv(1 << 16)
                    except (BlockingIOError, InterruptedError):
                        continue
                    except OSError:
                        data = b""
                    if not data:
                        sel.unregister(conn)
                        skipping.discard(conn)
                        tail = bufs.pop(conn, b"")
                        if tail:  # unterminated final line still counts
                            self._push(tail.decode("utf-8", errors="replace"))
                        try:
                            conn.close()
                        except OSError:
                            pass
                        if conn in self._conns:
                            self._conns.remove(conn)
                        continue
                    if conn in skipping:
                        # inside an oversized (already-dropped) line:
                        # discard until its terminating newline arrives
                        if b"\n" not in data:
                            continue
                        _, data = data.split(b"\n", 1)
                        skipping.discard(conn)
                    buf = bufs[conn] + data
                    *lines, rest = buf.split(b"\n")
                    if len(rest) > MAX_LINE_BYTES:
                        self.q.note_discarded()
                        obs.instant(
                            "listener.drop",
                            args={"listener": self.label, "cause": "oversize"},
                        )
                        rest = b""
                        skipping.add(conn)
                    bufs[conn] = rest
                    self._push_all([
                        raw.decode("utf-8", errors="replace").rstrip("\r")
                        for raw in lines
                    ])
        finally:
            sel.close()

    def _teardown(self) -> None:
        # snapshot: close() runs this on the caller's thread while the
        # receive loop may still be appending/removing connections
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


class FileTailer(BaseListener):
    """Rotating-file tailer: ``tail -F`` semantics for relay spool files.

    Follows ``path`` from its current end (or the start, for a file that
    appears later), detects rotation by inode change or truncation, and
    re-opens the new file from offset 0 so no post-rotation line is
    missed.  Partial trailing lines wait for their newline.
    """

    kind = "tail"

    def __init__(
        self, q: LineQueue, path: str, poll_sec: float = 0.1,
        from_start: bool = False,
    ):
        super().__init__(q, f"tail-{os.path.basename(path)}")
        self.path = path
        self.poll_sec = poll_sec
        # "pre-existing" is decided HERE, not at the serve thread's first
        # open attempt: a file created between construction and the
        # thread's first poll is NEW traffic and must be read from 0.
        # Deciding it at open time raced exactly that window — whether
        # the first lines survived depended on thread-spawn latency.
        self._from_start = from_start or not os.path.exists(path)

    @staticmethod
    def _ino(f) -> int:
        try:
            return os.fstat(f.fileno()).st_ino
        except OSError:
            return -1

    def _open(self):
        return open(self.path, "r", encoding="utf-8", errors="replace")

    def _serve(self) -> None:
        # Follow state lives on the instance, not in locals: a transient
        # fault re-enters _serve (listener.accept retry) and must resume
        # at the current file offset with its partial line intact — a
        # fresh f=None would reopen at offset 0 (_from_start is True by
        # then) and re-deliver every line already pushed.
        if not hasattr(self, "_f"):
            self._f, self._buf, self._skip = None, "", False
        while not self.stop_event.is_set():
            self._beat()
            if self._f is None:
                try:
                    self._f = self._open()
                except OSError:
                    # a file that appears later is NEW traffic: read it
                    # fully (only an already-present spool skips its past)
                    self._from_start = True
                    self.stop_event.wait(self.poll_sec)
                    continue
                if not self._from_start:
                    self._f.seek(0, os.SEEK_END)
                self._from_start = True  # rotated successors read fully
            chunk = self._f.read(1 << 16)
            if chunk:
                if self._skip:
                    if "\n" not in chunk:
                        continue
                    chunk = chunk.split("\n", 1)[1]
                    self._skip = False
                buf = self._buf + chunk
                *lines, buf = buf.split("\n")
                self._buf = buf
                self._push_all([line.rstrip("\r") for line in lines])
                if len(self._buf) > MAX_LINE_BYTES:
                    self.q.note_discarded()
                    obs.instant(
                        "listener.drop",
                        args={"listener": self.label, "cause": "oversize"},
                    )
                    self._buf = ""
                    self._skip = True
                continue
            # no new data: rotation (new inode) or truncation (shrunk)?
            try:
                st = os.stat(self.path)
                rotated = (
                    st.st_ino != self._ino(self._f)
                    or st.st_size < self._f.tell()
                )
            except OSError:
                rotated = True  # the old file was removed; wait for a new one
            if rotated:
                if self._buf:  # final unterminated line of the old file
                    self._push(self._buf)
                    self._buf = ""
                self._f.close()
                self._f = None
                continue
            self.stop_event.wait(self.poll_sec)
        if self._f is not None:
            self._f.close()
            self._f = None


def parse_listen_spec(spec: str) -> tuple[str, str, int | str]:
    """``udp:HOST:PORT`` / ``tcp:HOST:PORT`` / ``tail:PATH`` -> parts.

    Typed errors (AnalysisError) so the CLI reports a bad ``--listen``
    as usage, not a traceback.
    """
    kind, _, rest = spec.partition(":")
    if kind in ("tail", "tail0"):
        # tail = `tail -F` (skip a pre-existing file's past); tail0 =
        # read a pre-existing file from offset 0, then follow — replays
        # an already-written spool without racing the listener start
        if not rest:
            raise AnalysisError(f"bad --listen {spec!r}: {kind} needs a path")
        return (kind, "", rest)
    if kind in ("udp", "tcp"):
        host, _, port = rest.rpartition(":")
        if not host or not port:
            raise AnalysisError(
                f"bad --listen {spec!r}: want {kind}:HOST:PORT"
            )
        try:
            return (kind, host, int(port))
        except ValueError as e:
            raise AnalysisError(f"bad --listen port in {spec!r}") from e
    raise AnalysisError(
        f"bad --listen {spec!r}: kind must be udp, tcp, tail, or tail0"
    )


def make_listener(q: LineQueue, spec: str) -> BaseListener:
    kind, host, arg = parse_listen_spec(spec)
    if kind == "udp":
        return UdpSyslogListener(q, host, arg)
    if kind == "tcp":
        return TcpSyslogListener(q, host, arg)
    return FileTailer(q, str(arg), from_start=(kind == "tail0"))


def offset_listen_spec(spec: str, rank: int) -> str:
    """Per-host variant of one ``--listen`` spec (distributed serve).

    Each host of a ``serve --distributed`` deployment owns its own
    ingress, so a shared spec must fan out without colliding: fixed
    socket ports offset by ``rank`` (``tcp:H:6514`` -> ``tcp:H:6516``
    on host 2 — the conventional per-member port block), ephemeral
    port 0 stays 0 (every host binds its own, recorded per host in
    ``endpoint.json``), and tail paths gain a ``.host<rank>`` suffix
    (two tailers on one spool would double-count every line).
    Validates via :func:`parse_listen_spec`, so a bad spec fails at
    supervisor construction, not inside the Nth spawned worker.
    """
    kind, host, arg = parse_listen_spec(spec)
    if rank < 0:
        raise AnalysisError(f"listener host rank must be >= 0, got {rank}")
    if kind in ("udp", "tcp"):
        port = int(arg)
        return spec if port == 0 else f"{kind}:{host}:{port + rank}"
    return spec if rank == 0 else f"{kind}:{arg}.host{rank}"


class ListenerSet:
    """The ingress fleet: one queue, N listeners, liveness + gauges."""

    def __init__(self, q: LineQueue, specs: list[str]):
        self.q = q
        self.listeners: list[BaseListener] = []
        try:
            for s in specs:
                self.listeners.append(make_listener(q, s))
        except BaseException:
            # a failing Nth spec must not orphan the N-1 already-bound
            # sockets (the threads never start, so nothing else closes
            # them); close() on an unstarted listener is safe
            self.close()
            raise

    def start(self) -> None:
        for ln in self.listeners:
            ln.start()

    def close(self) -> None:
        for ln in self.listeners:
            ln.close()

    def alive(self) -> int:
        return sum(1 for ln in self.listeners if ln.is_alive() and not ln.dead)

    def stalled(self, age_sec: float) -> list[BaseListener]:
        """Live listeners whose heartbeat is older than ``age_sec``.

        A wedged receiver is worse than a dead one: it still looks alive
        while its traffic silently backs up and drops upstream.  The
        serve loop stamps overlapping windows incomplete and, when EVERY
        live listener is wedged with nothing queued, aborts typed
        (StallError) instead of idling forever.
        """
        now = time.monotonic()
        return [
            ln for ln in self.listeners
            if ln.is_alive() and not ln.dead and now - ln.beat > age_sec
        ]

    def first_error(self) -> BaseException | None:
        for ln in self.listeners:
            if ln.error is not None:
                return ln.error
        return None

    def addresses(self) -> dict[str, list[int | str]]:
        out: dict[str, list] = {}
        for ln in self.listeners:
            addr = getattr(ln, "address", None)
            out[ln.label] = list(addr) if addr else [getattr(ln, "path", "")]
        return out

    def sample_metrics(self) -> dict:
        """Queue/drop gauges for the metrics snapshotter (obs sampler)."""
        return {**self.q.snapshot(), "alive": self.alive(), "n": len(self.listeners)}
