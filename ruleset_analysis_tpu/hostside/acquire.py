"""Ruleset acquisition from a firewall inventory (SURVEY.md §4.1).

The reference's ``getaccesslists.py`` loops ``for firewall in
config.FIREWALLS``, obtains each firewall's configuration text, and
parses it.  This module is that loop: an inventory maps firewall name ->
source, where a source is either a path to a saved configuration file or
``cmd:<shell command>`` whose stdout is the configuration (the "fetch
from device" arm — e.g. ``cmd:ssh fw1 show running-config``).

The default inventory is ``config.FIREWALLS``; ``load_inventory`` also
reads a simple ``name = source`` text file so jobs can ship their own.
"""

from __future__ import annotations

import subprocess

from .. import config as config_mod
from .aclparse import AclParseError, Ruleset, parse_asa_config


def obtain_config(source: str, timeout: float = 60.0) -> str:
    """Configuration text for one inventory source (file or cmd:...).

    TRUST BOUNDARY: ``cmd:`` sources run through the shell verbatim
    (pipelines and ssh option strings are the point of the feature, as in
    the reference's fetch-from-device design), so an inventory file is
    executable configuration — treat it like a shell script.  Only point
    ``--inventory`` at operator-controlled files; never at files writable
    by untrusted users.

    Both arms decode permissively (device banners love stray bytes) and
    every failure mode — nonzero exit, hang past ``timeout`` — surfaces
    as :class:`AclParseError` so the CLI reports it cleanly.
    """
    if source.startswith("cmd:"):
        cmd = source[4:].strip()
        if not cmd:
            raise AclParseError(f"empty command in inventory source {source!r}")
        try:
            r = subprocess.run(
                cmd, shell=True, capture_output=True, timeout=timeout
            )
        except subprocess.TimeoutExpired:
            raise AclParseError(
                f"inventory command timed out after {timeout:.0f}s: {cmd!r}"
            ) from None
        if r.returncode != 0:
            err = r.stderr.decode("utf-8", errors="replace").strip()[:200]
            raise AclParseError(
                f"inventory command failed rc={r.returncode}: {cmd!r} ({err})"
            )
        return r.stdout.decode("utf-8", errors="replace")
    with open(source, "r", encoding="utf-8", errors="replace") as f:
        return f.read()


def load_inventory(path: str | None = None) -> dict[str, str]:
    """Inventory mapping firewall name -> source.

    ``path=None`` returns ``config.FIREWALLS`` (the reference's module
    constant).  A file holds one ``name = source`` pair per line;
    ``#`` comments and blank lines are ignored.
    """
    if path is None:
        return dict(config_mod.FIREWALLS)
    out: dict[str, str] = {}
    with open(path, "r", encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise AclParseError(
                    f"{path}:{lineno}: expected 'name = source', got {line!r}"
                )
            name, source = line.split("=", 1)
            out[name.strip()] = source.strip()
    return out


def iter_rulesets(inventory: dict[str, str], strict: bool = True):
    """Yield (name, source, Ruleset) per inventory entry, in order."""
    for name, source in inventory.items():
        text = obtain_config(source)
        yield name, source, parse_asa_config(text, name, strict=strict)


def acquire_rulesets(
    inventory: dict[str, str], strict: bool = True
) -> list[Ruleset]:
    """Obtain + parse every inventory entry, in inventory order."""
    return [rs for _, _, rs in iter_rulesets(inventory, strict=strict)]
