"""Parallel convert fleet: shard one corpus across worker processes
into pre-coalesced RAWIREv3 wire shards + a deterministic merge manifest.

ISSUE 11 / ROADMAP item 3: a single `convert` parses ~1.7-2.5M lines/s
per core, but an 8-chip mesh needs ~16.7M parsed lines/s aggregate —
convert must scale across cores the same way the feed plane does.  The
fleet applies the feeder's exact-raw-line descriptor model to convert:

- The coordinator chops the corpus into descriptors of exactly
  ``batch_size`` raw lines (``hostside.feeder._scan_batches`` — byte
  ranges only, native newline scanner, descriptors never span files) and
  assigns CONTIGUOUS descriptor ranges to N worker processes.
- Each worker parses its range with its own :class:`NativePacker` and
  writes one complete RAWIREv3 **weighted** shard: rows coalesce
  per-descriptor-batch into (unique row, weight) pairs — 20 B/row + the
  uint32 weights plane, the cheapest bytes a chip can be fed.
- The coordinator writes ``out`` as a MANIFEST: a small JSON file
  listing the shards in corpus order with their row/line accounting and
  the ruleset fingerprint.  ``run`` expands a manifest into its shard
  list and feeds them through the existing multi-file
  :class:`~.wire.WireReader`, which already concatenates payloads and
  counts resume offsets in stored-row units across files — so the fleet
  output is consumed as ONE corpus with bit-identical reports.

Determinism: the descriptor set is a pure function of (corpus bytes,
batch_size), workers only vary WHICH process handles a range, and
coalescing is per-batch — so the concatenated row stream (and therefore
every shard boundary, resume offset, and report) is byte-identical for
any worker count.  ``--workers 1`` is the reference the identity tests
pin ``--workers N`` against.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import pickle

import numpy as np

from ..errors import AnalysisError, FeedWorkerError
from . import fastparse
from .feeder import _scan_batches
from .pack import T_VALID, TUPLE_COLS, PackedRuleset
from .wire import (
    DEFAULT_BLOCK_ROWS,
    WireWriter,
    ruleset_fingerprint,
)

#: Manifest identity: first bytes of the JSON file, relied on by the
#: cheap sniff in :func:`is_manifest_file` (mirrors the wire magic).
MANIFEST_MAGIC = "RAWIRE-MANIFEST-v1"
_MANIFEST_PREFIX = ('{"magic": "' + MANIFEST_MAGIC + '"').encode()


def is_manifest_file(path: str) -> bool:
    """True if ``path`` is a convert-fleet manifest (cheap byte sniff)."""
    try:
        with open(path, "rb") as f:
            return f.read(len(_MANIFEST_PREFIX)) == _MANIFEST_PREFIX
    except OSError:
        return False


def read_manifest(path: str) -> dict:
    """Load + validate a manifest; shard paths resolve relative to it.

    The read IO runs under the central ``wire.read`` retry policy
    (runtime/retrypolicy.py): a transient open/read fault re-attempts
    with seeded backoff; a persistent one escalates as the typed
    AnalysisError below, exactly as before.
    """
    from ..runtime import faults, retrypolicy

    def _read():
        faults.fire("stream.wire.read.fail")
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    try:
        m = retrypolicy.call("wire.read", _read)
    except (OSError, ValueError) as e:
        raise AnalysisError(f"cannot read manifest {path!r}: {e}") from e
    if m.get("magic") != MANIFEST_MAGIC:
        raise AnalysisError(f"{path!r} is not a convert-fleet manifest")
    base = os.path.dirname(os.path.abspath(path))
    m["shard_paths"] = [
        s if os.path.isabs(s) else os.path.join(base, s)
        for s in (e["name"] for e in m["shards"])
    ]
    missing = [p for p in m["shard_paths"] if not os.path.exists(p)]
    if missing:
        raise AnalysisError(
            f"manifest {path!r} names missing shard(s): {missing[:3]}"
        )
    return m


def expand_wire_inputs(paths: list[str]) -> list[str]:
    """Replace each manifest in ``paths`` with its shard list, in order.

    Plain files (wire or text) pass through untouched, so callers can
    route the expanded list through the existing wire/text sniffing.
    """
    out: list[str] = []
    for p in paths:
        if p != "-" and is_manifest_file(p):
            out.extend(read_manifest(p)["shard_paths"])
        else:
            out.append(p)
    return out


def _shard_name(out_path: str, k: int, n: int) -> str:
    return f"{out_path}.shard{k:02d}-of-{n:02d}"


def _convert_descs(
    packed: PackedRuleset,
    paths: list[str],
    descs: list[tuple],
    shard_path: str,
    *,
    block_rows: int,
    batch_size: int,
    coalesce: bool,
) -> dict:
    """Parse one contiguous descriptor range into one complete shard.

    Runs inline for ``workers == 1`` and inside each spawned worker
    otherwise — one code path, so the reference and fleet outputs cannot
    drift.  Coalescing is per-descriptor-batch, which is what makes the
    row stream independent of how descriptors are grouped into shards.
    """
    from .pack import (
        coalesce_wire,
        coalesce_wire6,
        compact_batch,
        compact_batch6,
    )

    packer = fastparse.NativePacker(packed)
    rows_cap = (2 if packed.bindings_out else 1) * batch_size
    out = np.empty((TUPLE_COLS, rows_cap), dtype=np.uint32)
    files: dict[int, object] = {}
    w = WireWriter(
        shard_path, ruleset_fingerprint(packed), block_rows, weighted=coalesce
    )
    try:
        if packed.has_v6:
            w.begin6()
        last_skipped = 0
        for path_i, offset, nbytes, n_lines in descs:
            f = files.get(path_i)
            if f is None:
                f = files[path_i] = open(paths[path_i], "rb")
            f.seek(offset)
            data = f.read(nbytes)
            _, lines, _used = packer.pack_chunk(
                data, rows_cap, final=True, max_lines=n_lines, n_threads=1,
                out=out,
            )
            assert lines == n_lines  # descriptors are exact raw-line spans
            wire4 = compact_batch(out[:, out[T_VALID] == 1])
            if coalesce:
                wire4 = coalesce_wire(wire4)
            w.add(wire4, n_lines, packer.skipped - last_skipped)
            last_skipped = packer.skipped
            if packed.has_v6:
                rows6 = packer.take_v6()
                if len(rows6):
                    wire6 = compact_batch6(
                        np.asarray(rows6, dtype=np.uint32).T
                    )
                    if coalesce:
                        wire6 = coalesce_wire6(wire6)
                    w.add6(wire6, 0, 0)
        w.close()
    except BaseException:
        w.abort()  # partial magic: every reader refuses the torn shard
        raise
    finally:
        for f in files.values():
            f.close()
    return {
        "name": os.path.basename(shard_path),
        "rows": w.n_rows,
        "rows6": w.n6_rows,
        "raw_lines": w.raw_lines,
        "evals": w._evals if coalesce else w.n_rows + w.n6_rows,
        "skipped": w.n_skipped,
        "bytes": os.path.getsize(shard_path),
    }


def _fleet_worker(blob, paths, descs, shard_path, block_rows, batch_size,
                  coalesce, k, done_q):
    """Spawned worker: one descriptor range -> one shard; stats via queue."""
    from ..runtime import obs

    obs.note_role("convert-worker")
    try:
        packed = pickle.loads(blob)
        stats = _convert_descs(
            packed, paths, descs, shard_path,
            block_rows=block_rows, batch_size=batch_size, coalesce=coalesce,
        )
    except Exception as e:  # forward instead of dying silently
        done_q.put(("error", k, f"{type(e).__name__}: {e}"))
        return
    done_q.put(("ok", k, stats))


def convert_logs_fleet(
    packed: PackedRuleset,
    log_paths: list[str],
    out_path: str,
    *,
    workers: int,
    batch_size: int = DEFAULT_BLOCK_ROWS,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    coalesce: bool = True,
) -> dict:
    """Convert ``log_paths`` into ``workers`` wire shards + a manifest.

    Returns the aggregate stats dict (same keys as ``wire.convert_logs``
    plus ``workers``/``shards``).  Shards land next to ``out_path`` as
    ``<out>.shardKK-of-NN``; ``out_path`` itself becomes the manifest.
    A failed worker aborts the whole convert: its shard keeps the
    partial-magic header every reader refuses, and the coordinator
    removes all shard files before raising — never a silently short
    corpus.
    """
    if workers < 1:
        raise AnalysisError(f"convert fleet needs workers >= 1, got {workers}")
    if not fastparse.available():
        from ..errors import NativeParserUnavailable

        raise NativeParserUnavailable("convert --workers requires the native parser")
    descs = list(_scan_batches(list(log_paths), batch_size, 0))
    n_shards = min(workers, max(1, len(descs)))
    spans = [
        descs[k * len(descs) // n_shards:(k + 1) * len(descs) // n_shards]
        for k in range(n_shards)
    ]
    shard_paths = [_shard_name(out_path, k, n_shards) for k in range(n_shards)]

    per_shard: list[dict | None] = [None] * n_shards
    try:
        if n_shards == 1:
            per_shard[0] = _convert_descs(
                packed, list(log_paths), spans[0], shard_paths[0],
                block_rows=block_rows, batch_size=batch_size,
                coalesce=coalesce,
            )
        else:
            # spawn, not fork: the caller may run JAX thread pools, and
            # the workers import only numpy + the native parser
            ctx = multiprocessing.get_context("spawn")
            done_q = ctx.Queue()
            blob = pickle.dumps(packed)
            procs = [
                ctx.Process(
                    target=_fleet_worker,
                    args=(blob, list(log_paths), spans[k], shard_paths[k],
                          block_rows, batch_size, coalesce, k, done_q),
                    daemon=True,
                )
                for k in range(n_shards)
            ]
            for p in procs:
                p.start()
            try:
                got = 0
                while got < n_shards:
                    try:
                        msg = done_q.get(timeout=5.0)
                    except Exception:
                        dead = [p.pid for p in procs if not p.is_alive()]
                        if dead and got < n_shards:
                            # a worker died without reporting (OOM-kill
                            # analog) — check again after a beat in case
                            # its message is still in flight
                            try:
                                msg = done_q.get(timeout=2.0)
                            except Exception:
                                raise FeedWorkerError(
                                    f"convert worker(s) {dead} died without "
                                    "reporting (killed by the OS?)"
                                ) from None
                        else:
                            continue
                    if msg[0] == "error":
                        raise FeedWorkerError(
                            f"convert worker {msg[1]} failed: {msg[2]}"
                        )
                    _, k, stats = msg
                    per_shard[k] = stats
                    got += 1
            finally:
                for p in procs:
                    p.join(timeout=10)
                for p in procs:
                    if p.is_alive():
                        p.terminate()
                for p in procs:
                    p.join(timeout=5)
                done_q.cancel_join_thread()
                done_q.close()
    except BaseException:
        for sp in shard_paths:
            try:
                os.unlink(sp)
            except OSError:
                pass
        raise

    totals = {
        key: sum(s[key] for s in per_shard)
        for key in ("rows", "rows6", "raw_lines", "evals", "skipped", "bytes")
    }
    manifest = {
        "magic": MANIFEST_MAGIC,
        "fingerprint": ruleset_fingerprint(packed).hex(),
        "weighted": coalesce,
        "block_rows": block_rows,
        "batch_size": batch_size,
        "workers": n_shards,
        **totals,
        "shards": per_shard,
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        # no indent: the sniff in is_manifest_file keys on the first
        # bytes being exactly '{"magic": "RAWIRE-MANIFEST-v1"'
        json.dump(manifest, f)
        f.write("\n")
    os.replace(tmp, out_path)  # atomic: a crashed convert leaves no manifest
    return {
        **totals,
        "bytes": totals["bytes"],
        "parser": f"fleet-x{n_shards}",
        "weighted": coalesce,
        "workers": n_shards,
        "shards": [s["name"] for s in per_shard],
    }
