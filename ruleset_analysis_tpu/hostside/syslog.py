"""Cisco ASA syslog parsing: log line -> (firewall, ACL, 5-tuple).

The reference's ``mapper.py`` (SURVEY.md §4.3) regex-parses each syslog line,
extracts the connection 5-tuple plus the firewall identity, and decides which
ACL to evaluate.  This module is that parse step, host-side and backend-
agnostic: both the exact oracle and the TPU packer consume its output.

Message classes handled (the classes SURVEY.md §4.3 names):

- ``%ASA-n-106100``: ``access-list <acl> permitted|denied <proto>
  <if>/<src>(<sport>) -> <if>/<dst>(<dport>) hit-cnt ...`` — names the ACL
  directly.
- ``%ASA-n-106023``: ``Deny <proto> src <if>:<src>[/<sport>] dst
  <if>:<dst>[/<dport>] [(type <t>, code <c>)] by access-group "<acl>"``.
- ``%ASA-n-302013/302015``: ``Built inbound|outbound TCP|UDP connection <id>
  for <if>:<a>/<p> (...) to <if>:<b>/<q> (...)`` — no ACL in the message;
  the ACL is resolved from the ingress interface's ``access-group`` binding.

ICMP convention (shared with aclparse): the ICMP *type* travels in the
destination-port column and the source port is 0, so one packed tuple layout
serves every protocol.
"""

from __future__ import annotations

import dataclasses
import re

from .aclparse import PROTO_NUMBERS, ip_to_u32


@dataclasses.dataclass(frozen=True)
class ParsedLine:
    """One successfully parsed ASA log line, ACL not yet resolved."""

    firewall: str
    acl: str | None  # None for connection messages; resolve via binding
    ingress_if: str | None
    proto: int
    src: int
    sport: int
    dst: int
    dport: int
    permitted: bool | None  # what the firewall says it did (106100/106023)


_PROTO_BY_NAME = {k: (v if v is not None else 0) for k, v in PROTO_NUMBERS.items()}


def _proto_num(tok: str) -> int:
    v = _PROTO_BY_NAME.get(tok.lower())
    if v is not None:
        return v
    try:
        return int(tok)
    except ValueError:
        return 0


# hostname is the last whitespace token before the %ASA tag (syslog relay
# prefixes vary; this is robust to "<pri>MMM dd hh:mm:ss host : %ASA-...").
_TAG_RE = re.compile(r"(?:^|\s)(\S+?)\s*:?\s*%ASA-\d-(\d{6}):\s*(.*)$")

_M106100_RE = re.compile(
    r"access-list\s+(\S+)\s+(permitted|denied|est-allowed)\s+(\S+)\s+"
    r"(\S+?)/([\d.]+)\((\d+)\)(?:\([^)]*\))?\s*->\s*"
    r"(\S+?)/([\d.]+)\((\d+)\)"
)

_M106023_RE = re.compile(
    r"Deny\s+(\S+)\s+src\s+(\S+?):([\d.]+)(?:/(\d+))?\s+"
    r"dst\s+(\S+?):([\d.]+)(?:/(\d+))?"
    r"(?:\s+\(type\s+(\d+),\s*code\s+(\d+)\))?"
    r'.*?by\s+access-group\s+"([^"]+)"'
)

_M302013_RE = re.compile(
    r"Built\s+(inbound|outbound)\s+(TCP|UDP)\s+connection\s+\S+\s+for\s+"
    r"(\S+?):([\d.]+)/(\d+)\s*(?:\([^)]*\))?\s*to\s+"
    r"(\S+?):([\d.]+)/(\d+)"
)


def parse_line(line: str) -> ParsedLine | None:
    """Parse one raw syslog line; None if it is not a handled ASA message."""
    m = _TAG_RE.search(line)
    if not m:
        return None
    host, msgid, body = m.group(1), m.group(2), m.group(3)

    if msgid == "106100":
        b = _M106100_RE.search(body)
        if not b:
            return None
        acl, verdict, proto_tok = b.group(1), b.group(2), b.group(3)
        proto = _proto_num(proto_tok)
        sport = int(b.group(6))
        dport = int(b.group(9))
        if proto == 1:
            # ICMP: the parenthesised values are type/code; type -> dport
            dport = sport
            sport = 0
        return ParsedLine(
            firewall=host,
            acl=acl,
            ingress_if=b.group(4),
            proto=proto,
            src=ip_to_u32(b.group(5)),
            sport=sport,
            dst=ip_to_u32(b.group(8)),
            dport=dport,
            permitted=(verdict != "denied"),
        )

    if msgid == "106023":
        b = _M106023_RE.search(body)
        if not b:
            return None
        proto = _proto_num(b.group(1))
        sport = int(b.group(4) or 0)
        dport = int(b.group(7) or 0)
        if proto == 1 and b.group(8) is not None:
            dport = int(b.group(8))  # icmp type
            sport = 0
        return ParsedLine(
            firewall=host,
            acl=b.group(10),
            ingress_if=b.group(2),
            proto=proto,
            src=ip_to_u32(b.group(3)),
            sport=sport,
            dst=ip_to_u32(b.group(6)),
            dport=dport,
            permitted=False,
        )

    if msgid in ("302013", "302015"):
        b = _M302013_RE.search(body)
        if not b:
            return None
        direction = b.group(1)
        proto = 6 if b.group(2) == "TCP" else 17
        if_a, ip_a, port_a = b.group(3), ip_to_u32(b.group(4)), int(b.group(5))
        if_b, ip_b, port_b = b.group(6), ip_to_u32(b.group(7)), int(b.group(8))
        # "Built ... for A to B": A is the lower-security side.  Inbound
        # connections are initiated at A (src=A); outbound are initiated at B
        # (src=B) with A as the destination side.
        if direction == "inbound":
            src, sport, dst, dport, ingress = ip_a, port_a, ip_b, port_b, if_a
        else:
            src, sport, dst, dport, ingress = ip_b, port_b, ip_a, port_a, if_b
        return ParsedLine(
            firewall=host,
            acl=None,
            ingress_if=ingress,
            proto=proto,
            src=src,
            sport=sport,
            dst=dst,
            dport=dport,
            permitted=True,
        )

    return None
