"""Cisco ASA syslog parsing: log line -> (firewall, ACL, 5-tuple).

The reference's ``mapper.py`` (SURVEY.md §4.3) regex-parses each syslog line,
extracts the connection 5-tuple plus the firewall identity, and decides which
ACL to evaluate.  This module is that parse step, host-side and backend-
agnostic: both the exact oracle and the TPU packer consume its output.

Message classes handled (the ASA access-list / connection family SURVEY.md
§4.3 names):

- ``%ASA-n-106100``: ``access-list <acl> permitted|denied <proto>
  <if>/<src>(<sport>) -> <if>/<dst>(<dport>) hit-cnt ...`` — names the ACL
  directly.
- ``%ASA-n-106023``: ``Deny <proto> src <if>:<src>[/<sport>] dst
  <if>:<dst>[/<dport>] [(type <t>, code <c>)] by access-group "<acl>"``.
- ``%ASA-n-106001``: ``Inbound TCP connection denied from <src>/<sport> to
  <dst>/<dport> flags <f> on interface <if>`` — resolved via the
  interface's ``in`` binding.
- ``%ASA-n-106006``: ``Deny inbound UDP from <src>/<sport> to <dst>/<dport>
  on interface <if>`` — resolved via the ``in`` binding.
- ``%ASA-n-106015``: ``Deny TCP (no connection) from <src>/<sport> to
  <dst>/<dport> flags <f> on interface <if>`` — resolved via the ``in``
  binding.
- ``%ASA-n-302013/302015``: ``Built inbound|outbound TCP|UDP connection <id>
  for <if>:<a>/<p> (...) to <if>:<b>/<q> (...)`` — no ACL in the message;
  resolved from the ingress interface's ``in`` binding AND (when
  configured) the egress interface's ``out`` binding — one connection line
  can be evaluated against both.

ICMP convention (shared with aclparse): the ICMP *type* travels in the
destination-port column and the source port is 0, so one packed tuple layout
serves every protocol.
"""

from __future__ import annotations

import dataclasses
import re

from .aclparse import FAM_V4, FAM_V6, PROTO_NUMBERS, ip6_to_int, ip_to_u32


@dataclasses.dataclass(frozen=True)
class ParsedLine:
    """One successfully parsed ASA log line, ACL not yet resolved.

    ``family`` is FAM_V4 or FAM_V6; src/dst are Python ints (32- or
    128-bit).  ASA logs a connection's two endpoints in one family —
    mixed-family text in a single message is malformed and skipped.
    """

    firewall: str
    acl: str | None  # None for connection messages; resolve via binding
    ingress_if: str | None
    proto: int
    src: int
    sport: int
    dst: int
    dport: int
    permitted: bool | None  # what the firewall says it did (106100/106023)
    #: exit interface (302013/302015 only): evaluated against that
    #: interface's ``out`` access-group binding, when one exists
    egress_if: str | None = None
    family: int = FAM_V4


_PROTO_BY_NAME = {k: (v if v is not None else 0) for k, v in PROTO_NUMBERS.items()}


def _proto_num(tok: str) -> int:
    v = _PROTO_BY_NAME.get(tok.lower())
    if v is not None:
        return v
    try:
        return int(tok)
    except ValueError:
        return 0


def _addr(tok: str) -> tuple[int, int]:
    """Address text -> (family, value); v6 recognised by colon literals.

    Raises (a ValueError subclass) on malformed text of either family —
    parse_line turns that into a clean line skip.
    """
    if ":" in tok:
        return FAM_V6, ip6_to_int(tok)
    return FAM_V4, ip_to_u32(tok)


# hostname is the last whitespace token before the %ASA tag (syslog relay
# prefixes vary; this is robust to "<pri>MMM dd hh:mm:ss host : %ASA-...").
# re.ASCII everywhere: Python's \d otherwise matches Unicode digits,
# which int() accepts but the native parser (asaparse.cpp is_dig,
# ASCII-only) rejects — the two parsers must agree on every input
# (mirrors the ip_to_u32 isascii() guard).
_TAG_RE = re.compile(r"(?:^|\s)(\S+?)\s*:?\s*%ASA-\d-(\d{6}):\s*(.*)$", re.ASCII)

_M106100_RE = re.compile(
    r"access-list\s+(\S+)\s+(permitted|denied|est-allowed)\s+(\S+)\s+"
    r"(\S+?)/([\dA-Fa-f:.]+)\((\d+)\)(?:\([^)]*\))?\s*->\s*"
    r"(\S+?)/([\dA-Fa-f:.]+)\((\d+)\)"
    , re.ASCII
)

_M106023_RE = re.compile(
    r"Deny\s+(\S+)\s+src\s+(\S+?):([\dA-Fa-f:.]+)(?:/(\d+))?\s+"
    r"dst\s+(\S+?):([\dA-Fa-f:.]+)(?:/(\d+))?"
    r"(?:\s+\(type\s+(\d+),\s*code\s+(\d+)\))?"
    r'.*?by\s+access-group\s+"([^"]+)"'
    , re.ASCII
)

_M302013_RE = re.compile(
    r"Built\s+(inbound|outbound)\s+(TCP|UDP)\s+connection\s+\S+\s+for\s+"
    r"(\S+?):([\dA-Fa-f:.]+)/(\d+)\s*(?:\([^)]*\))?\s*to\s+"
    r"(\S+?):([\dA-Fa-f:.]+)/(\d+)"
    , re.ASCII
)

_M106001_RE = re.compile(
    r"Inbound\s+TCP\s+connection\s+denied\s+from\s+([\dA-Fa-f:.]+)/(\d+)\s+to\s+"
    r"([\dA-Fa-f:.]+)/(\d+)\s+flags\s+.*?\bon\s+interface\s+(\S+)"
    , re.ASCII
)

_M106006_RE = re.compile(
    r"Deny\s+inbound\s+UDP\s+from\s+([\dA-Fa-f:.]+)/(\d+)\s+to\s+"
    r"([\dA-Fa-f:.]+)/(\d+)\s+on\s+interface\s+(\S+)"
    , re.ASCII
)

_M106015_RE = re.compile(
    r"Deny\s+TCP\s+\(no connection\)\s+from\s+([\dA-Fa-f:.]+)/(\d+)\s+to\s+"
    r"([\dA-Fa-f:.]+)/(\d+)\s+flags\s+.*?\bon\s+interface\s+(\S+)"
    , re.ASCII
)


def _field_ranges_ok(p: ParsedLine) -> ParsedLine | None:
    """Skip lines whose numeric fields exceed their wire widths.

    Ports are 16-bit and protocol numbers 8-bit on the wire (and in the
    bit-packed device batch layout, pack.compact_batch); a syslog line
    claiming port 70000 is malformed, and silently truncating it could
    make it match a rule it shouldn't.  The native C++ parser applies the
    identical post-parse check, keeping the two paths line-for-line equal.
    """
    if p.sport > 0xFFFF or p.dport > 0xFFFF or p.proto > 0xFF:
        return None
    return p


def parse_line(line: str) -> ParsedLine | None:
    """Parse one raw syslog line; None if it is not a handled ASA message."""
    try:
        p = _parse_line_raw(line)
    except ValueError:
        # An ASA-shaped line with a malformed field (e.g. a corrupt
        # address like "1.2.3.4.5.6" — r5 fuzz) is not a handled message:
        # skip it like any other unparseable line instead of letting
        # ip_to_u32's AclParseError crash the whole chunk loop.
        return None
    return None if p is None else _field_ranges_ok(p)


def _parse_line_raw(line: str) -> ParsedLine | None:
    m = _TAG_RE.search(line)
    if not m:
        return None
    host, msgid, body = m.group(1), m.group(2), m.group(3)

    if msgid == "106100":
        b = _M106100_RE.search(body)
        if not b:
            return None
        acl, verdict, proto_tok = b.group(1), b.group(2), b.group(3)
        proto = _proto_num(proto_tok)
        sport = int(b.group(6))
        dport = int(b.group(9))
        if proto in (1, 58):
            # ICMP/ICMPv6: the parenthesised values are type/code; type -> dport
            dport = sport
            sport = 0
        sfam, src = _addr(b.group(5))
        dfam, dst = _addr(b.group(8))
        if sfam != dfam:
            return None
        return ParsedLine(
            firewall=host,
            acl=acl,
            ingress_if=b.group(4),
            proto=proto,
            src=src,
            sport=sport,
            dst=dst,
            dport=dport,
            permitted=(verdict != "denied"),
            family=sfam,
        )

    if msgid == "106023":
        b = _M106023_RE.search(body)
        if not b:
            return None
        proto = _proto_num(b.group(1))
        sport = int(b.group(4) or 0)
        dport = int(b.group(7) or 0)
        if proto in (1, 58) and b.group(8) is not None:
            dport = int(b.group(8))  # icmp type
            sport = 0
        sfam, src = _addr(b.group(3))
        dfam, dst = _addr(b.group(6))
        if sfam != dfam:
            return None
        return ParsedLine(
            firewall=host,
            acl=b.group(10),
            ingress_if=b.group(2),
            proto=proto,
            src=src,
            sport=sport,
            dst=dst,
            dport=dport,
            permitted=False,
            family=sfam,
        )

    if msgid in ("302013", "302015"):
        b = _M302013_RE.search(body)
        if not b:
            return None
        direction = b.group(1)
        proto = 6 if b.group(2) == "TCP" else 17
        fam_a, ip_a = _addr(b.group(4))
        fam_b, ip_b = _addr(b.group(7))
        if fam_a != fam_b:
            return None
        if_a, port_a = b.group(3), int(b.group(5))
        if_b, port_b = b.group(6), int(b.group(8))
        # "Built ... for A to B": A is the lower-security side.  Inbound
        # connections are initiated at A (src=A); outbound are initiated at B
        # (src=B) with A as the destination side.  The packet enters on the
        # initiator's interface and exits on the other — the egress side's
        # ``out`` ACL (if bound) also filters it.
        if direction == "inbound":
            src, sport, dst, dport = ip_a, port_a, ip_b, port_b
            ingress, egress = if_a, if_b
        else:
            src, sport, dst, dport = ip_b, port_b, ip_a, port_a
            ingress, egress = if_b, if_a
        return ParsedLine(
            firewall=host,
            acl=None,
            ingress_if=ingress,
            proto=proto,
            src=src,
            sport=sport,
            dst=dst,
            dport=dport,
            permitted=True,
            egress_if=egress,
            family=fam_a,
        )

    if msgid in ("106001", "106006", "106015"):
        rx = {"106001": _M106001_RE, "106006": _M106006_RE, "106015": _M106015_RE}[msgid]
        b = rx.search(body)
        if not b:
            return None
        sfam, src = _addr(b.group(1))
        dfam, dst = _addr(b.group(3))
        if sfam != dfam:
            return None
        return ParsedLine(
            firewall=host,
            acl=None,
            ingress_if=b.group(5),
            proto=17 if msgid == "106006" else 6,
            src=src,
            sport=int(b.group(2)),
            dst=dst,
            dport=int(b.group(4)),
            permitted=False,
            family=sfam,
        )

    return None
