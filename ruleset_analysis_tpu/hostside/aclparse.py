"""Cisco ASA access-list parser with object-group expansion.

This is the host half of the reference's L1 layer (``getaccesslists.py``,
SURVEY.md §3/§4.1): read an ASA configuration, extract ``access-list`` lines,
resolve ``object-group`` / ``object`` references, and expand each configured
rule into concrete match rows.

Design decision for the TPU rebuild (SURVEY.md §8.0): every matchable field
is normalised to an **inclusive uint32 range** ``[lo, hi]`` —

- addresses: ``host A`` -> [a, a]; ``NET MASK`` -> [net, net | ~mask];
  ``range A B`` -> [a, b]; ``any`` -> [0, 2**32-1]
- ports: ``eq p`` -> [p, p]; ``range a b``; ``gt p``; ``lt p``; ``neq p``
  (expands into two rows); absent -> [0, 65535]
- protocols: ``tcp`` -> [6, 6]; ``ip`` -> [0, 255]
- ICMP types are carried in the destination-port column ([type, type]),
  mirroring how the syslog parser packs ICMP messages.

so the device-side predicate is five branch-free range tests.  One configured
rule (one config line — the unit the unused-rule report counts) expands into
the cross-product of its object-group alternatives, exactly as the reference
expands groups on the host before shipping rules to map tasks.
"""

from __future__ import annotations

import dataclasses
import re

PERMIT, DENY = 1, 0

U32_MAX = 0xFFFFFFFF
U128_MAX = (1 << 128) - 1
PORT_MAX = 0xFFFF

#: Address families.  ``FAM_WILD`` marks a family-agnostic wildcard
#: (``any`` / ``interface``) during expansion; it never survives into an
#: :class:`Ace` — parse_asa_config resolves it per ruleset (v4-only for
#: pure-v4 configs, both families when the ruleset carries explicit v6
#: content — the ASA 9.0+ unified-ACL reading of ``any``, gated so
#: v4-era configs keep their exact pre-v6 expansion).
FAM_WILD, FAM_V4, FAM_V6 = 0, 4, 6

#: IP protocol names ASA accepts in ACEs.
PROTO_NUMBERS = {
    "ip": None,  # any protocol -> [0, 255]
    "icmp": 1,
    "igmp": 2,
    "ipinip": 4,
    "tcp": 6,
    "udp": 17,
    "gre": 47,
    "esp": 50,
    "ah": 51,
    "icmp6": 58,
    "eigrp": 88,
    "ospf": 89,
    "nos": 94,
    "pim": 103,
    "pcp": 108,
    "snp": 109,
    "sctp": 132,
}

#: TCP/UDP service names ASA commonly resolves in port specs.
PORT_NAMES = {
    "echo": 7,
    "discard": 9,
    "daytime": 13,
    "chargen": 19,
    "ftp-data": 20,
    "ftp": 21,
    "ssh": 22,
    "telnet": 23,
    "smtp": 25,
    "time": 37,
    "whois": 43,
    "tacacs": 49,
    "domain": 53,
    "bootps": 67,
    "bootpc": 68,
    "tftp": 69,
    "gopher": 70,
    "finger": 79,
    "http": 80,
    "www": 80,
    "kerberos": 88,
    "hostname": 101,
    "pop2": 109,
    "pop3": 110,
    "sunrpc": 111,
    "ident": 113,
    "nntp": 119,
    "ntp": 123,
    "netbios-ns": 137,
    "netbios-dgm": 138,
    "netbios-ssn": 139,
    "imap4": 143,
    "snmp": 161,
    "snmptrap": 162,
    "bgp": 179,
    "irc": 194,
    "ldap": 389,
    "https": 443,
    "isakmp": 500,
    "exec": 512,
    "login": 513,
    "rsh": 514,
    "syslog": 514,
    "lpd": 515,
    "talk": 517,
    "rip": 520,
    "uucp": 540,
    "klogin": 543,
    "kshell": 544,
    "ldaps": 636,
    "kerberos-adm": 749,
    "pptp": 1723,
    "radius": 1645,
    "radius-acct": 1646,
    "sip": 5060,
    "aol": 5190,
    "pcanywhere-data": 5631,
    "pcanywhere-status": 5632,
}

#: ICMP type names usable after the destination in an icmp ACE.
ICMP_TYPE_NAMES = {
    "echo-reply": 0,
    "unreachable": 3,
    "source-quench": 4,
    "redirect": 5,
    "echo": 8,
    "router-advertisement": 9,
    "router-solicitation": 10,
    "time-exceeded": 11,
    "parameter-problem": 12,
    "timestamp-request": 13,
    "timestamp-reply": 14,
    "information-request": 15,
    "information-reply": 16,
    "mask-request": 17,
    "mask-reply": 18,
    "traceroute": 30,
}

#: ICMPv6 type names (RFC 4443 / 4861) usable after the destination in an
#: ``icmp6`` ACE — the numbers differ from their v4 namesakes (echo-reply
#: is 129, not 0), so icmp6 entries resolve through THIS table.
ICMP6_TYPE_NAMES = {
    "unreachable": 1,
    "packet-too-big": 2,
    "time-exceeded": 3,
    "parameter-problem": 4,
    "echo": 128,
    "echo-reply": 129,
    "membership-query": 130,
    "membership-report": 131,
    "membership-reduction": 132,
    "router-solicitation": 133,
    "router-advertisement": 134,
    "neighbor-solicitation": 135,
    "neighbor-advertisement": 136,
    "neighbor-redirect": 137,
    "router-renumbering": 138,
}

FULL_PORTS = (0, PORT_MAX)
FULL_ADDR = (0, U32_MAX)
FULL_ADDR6 = (0, U128_MAX)
FULL_PROTO = (0, 255)

#: Family-tagged full-range address alternatives ((family, lo, hi) —
#: the shape every address resolver returns).
ANY4 = (FAM_V4, 0, U32_MAX)
ANY6 = (FAM_V6, 0, U128_MAX)
ANY_WILD = (FAM_WILD, 0, 0)  # bounds resolved at family expansion


class AclParseError(ValueError):
    """Raised on configuration text this parser cannot interpret."""


def ip_to_u32(s: str) -> int:
    # v6 literals are parsed by ip6_to_int; reaching here with one means
    # the CONTEXT is v4-only (e.g. a standard ACL) — say so explicitly,
    # the lenient-mode skip accounting surfaces this reason verbatim.
    if ":" in s or s == "any6":
        raise AclParseError(f"IPv6 address in IPv4-only context: {s!r}")
    parts = s.split(".")
    if len(parts) != 4:
        raise AclParseError(f"bad IPv4 address: {s!r}")
    v = 0
    for p in parts:
        # plain ASCII digits only: int() also accepts "+1", "1_0", and
        # Unicode digits, which the native parser (asaparse.cpp
        # parse_ipv4_run — documented ip_to_u32 semantics) rejects; the
        # two paths must agree on every input.  Non-numeric octets (fuzz:
        # "1..2.3") must raise the clean parse error, not a raw
        # ValueError that escapes the lenient-mode skip handler.
        if not (p.isascii() and p.isdigit()):
            raise AclParseError(f"bad IPv4 address: {s!r}")
        b = int(p)
        if not 0 <= b <= 255:
            raise AclParseError(f"bad IPv4 address: {s!r}")
        v = (v << 8) | b
    return v


def u32_to_ip(v: int) -> str:
    return ".".join(str((v >> s) & 0xFF) for s in (24, 16, 8, 0))


def subnet_range(net: str, mask: str) -> tuple[int, int]:
    n, m = ip_to_u32(net), ip_to_u32(mask)
    lo = n & m
    return lo, lo | (~m & U32_MAX)


def ip6_to_int(s: str) -> int:
    """IPv6 literal -> 128-bit int (RFC 4291 text forms, incl. embedded v4).

    Delegates to the stdlib ``ipaddress`` parser — strict (rejects zone
    ids, malformed compressions) and battle-tested; the device side never
    sees text, only the 4x uint32 limbs pack.py derives from this int.
    """
    import ipaddress

    try:
        return int(ipaddress.IPv6Address(s))
    except (ipaddress.AddressValueError, ValueError):
        raise AclParseError(f"bad IPv6 address: {s!r}") from None


def int_to_ip6(v: int) -> str:
    import ipaddress

    return str(ipaddress.IPv6Address(v))


def prefix6_range(tok: str) -> tuple[int, int]:
    """``2001:db8::/64`` -> inclusive [lo, hi].

    The ``/prefixlen`` is REQUIRED: ASA spells v6 network operands as one
    prefix token (never address + mask pairs), and accepting a bare
    literal here would let a v4-style ``ADDR MASK`` v6 spelling silently
    parse as two /128 operands — a mis-parse, not a lenient read.  Bare
    literals are only valid after ``host``.
    """
    if "/" not in tok:
        raise AclParseError(
            f"IPv6 network operand requires /prefixlen (or use host): {tok!r}"
        )
    addr, _, plen_s = tok.partition("/")
    if not (plen_s.isascii() and plen_s.isdigit()) or not 0 <= int(plen_s) <= 128:
        raise AclParseError(f"bad IPv6 prefix length: {tok!r}")
    plen = int(plen_s)
    a = ip6_to_int(addr)
    mask = (U128_MAX << (128 - plen)) & U128_MAX
    lo = a & mask
    return lo, lo | (~mask & U128_MAX)


def _port_value(tok: str) -> int:
    if tok in PORT_NAMES:
        return PORT_NAMES[tok]
    try:
        v = int(tok)
    except ValueError:
        raise AclParseError(f"unknown port {tok!r}") from None
    if not 0 <= v <= PORT_MAX:
        raise AclParseError(f"port out of range: {tok!r}")
    return v


def _proto_ranges(tok: str) -> list[tuple[int, int]]:
    if tok in PROTO_NUMBERS:
        n = PROTO_NUMBERS[tok]
        return [FULL_PROTO] if n is None else [(n, n)]
    try:
        v = int(tok)
    except ValueError:
        raise AclParseError(f"unknown protocol {tok!r}") from None
    if not 0 <= v <= 255:
        raise AclParseError(f"protocol out of range: {tok!r}")
    return [(v, v)]


# ---------------------------------------------------------------------------
# Data model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Ace:
    """One concrete, fully-expanded match row (all-inclusive ranges).

    ``family`` is FAM_V4 or FAM_V6; address bounds are Python ints (32-
    or 128-bit).  A packet can only match an ACE of its own family —
    pack.py exploits this to split rows into per-family device tensors
    without breaking first-match order (cross-family matches are
    impossible, so the min-matching-row within a family equals the
    min-matching-row overall for that packet).
    """

    action: int  # PERMIT / DENY
    proto_lo: int
    proto_hi: int
    src_lo: int
    src_hi: int
    sport_lo: int
    sport_hi: int
    dst_lo: int
    dst_hi: int
    dport_lo: int
    dport_hi: int
    family: int = FAM_V4

    def matches(
        self, proto: int, src: int, sport: int, dst: int, dport: int,
        family: int = FAM_V4,
    ) -> bool:
        return (
            self.family == family
            and self.proto_lo <= proto <= self.proto_hi
            and self.src_lo <= src <= self.src_hi
            and self.sport_lo <= sport <= self.sport_hi
            and self.dst_lo <= dst <= self.dst_hi
            and self.dport_lo <= dport <= self.dport_hi
        )


@dataclasses.dataclass
class AclRule:
    """One configured access-list entry (one config line).

    This is the unit of the unused-rule report — the reference emits hit
    counts keyed by the configured rule, not by expanded alternative
    (SURVEY.md §4.3/§4.5).
    """

    acl: str
    index: int  # 1-based position among real entries of this ACL
    text: str  # original configuration line
    aces: list[Ace] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Ruleset:
    """All parsed ACLs of one firewall (the L1->L3 contract)."""

    firewall: str
    acls: dict[str, list[AclRule]] = dataclasses.field(default_factory=dict)
    #: (interface name, direction) -> acl name, from ``access-group`` lines.
    #: Keyed by direction too: one interface can carry BOTH an ``in`` and
    #: an ``out`` ACL, and egress bindings are evaluated for connection
    #: messages just like ingress ones.
    bindings: dict[tuple[str, str], str] = dataclasses.field(default_factory=dict)
    #: Lenient-mode skips: (line number, reason, raw line) for every
    #: access-list entry ``parse_asa_config(strict=False)`` could not
    #: support (IPv6, exotic object members, ...).  A skipped entry still
    #: consumes its rule index, so later rules keep their device-side
    #: positions.  Empty in strict mode (errors raise instead).
    skipped: list[tuple[int, str, str]] = dataclasses.field(default_factory=list)

    def rule_count(self) -> int:
        return sum(len(rules) for rules in self.acls.values())

    def ace_count(self) -> int:
        return sum(len(r.aces) for rules in self.acls.values() for r in rules)


# ---------------------------------------------------------------------------
# Object / object-group resolution
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Groups:
    network: dict[str, list] = dataclasses.field(default_factory=dict)
    service: dict[str, dict] = dataclasses.field(default_factory=dict)
    protocol: dict[str, list] = dataclasses.field(default_factory=dict)
    icmp_type: dict[str, list] = dataclasses.field(default_factory=dict)
    net_objects: dict[str, list] = dataclasses.field(default_factory=dict)
    svc_objects: dict[str, list] = dataclasses.field(default_factory=dict)


def _collect_blocks(lines: list[str]) -> tuple[_Groups, list[tuple[int, str]]]:
    """One pass: gather object/object-group blocks; return remaining lines."""
    groups = _Groups()
    rest: list[tuple[int, str]] = []
    i = 0
    n = len(lines)
    while i < n:
        line = lines[i].rstrip()
        stripped = line.strip()
        toks = stripped.split()
        if not toks:
            i += 1
            continue
        if toks[0] == "object-group" and len(toks) >= 3:
            kind, name = toks[1], toks[2]
            body: list[list[str]] = []
            i += 1
            while i < n and (lines[i].startswith((" ", "\t"))):
                t = lines[i].split()
                if t and t[0] != "description":
                    body.append(t)
                i += 1
            if kind == "network":
                groups.network[name] = body
            elif kind == "service":
                proto = toks[3] if len(toks) > 3 else None  # tcp|udp|tcp-udp|None
                groups.service[name] = {"proto": proto, "body": body}
            elif kind == "protocol":
                groups.protocol[name] = body
            elif kind == "icmp-type":
                groups.icmp_type[name] = body
            # other kinds (user, security) are not matchable here; ignore
            continue
        if toks[0] == "object" and len(toks) >= 3:
            kind, name = toks[1], toks[2]
            body = []
            i += 1
            while i < n and lines[i].startswith((" ", "\t")):
                t = lines[i].split()
                if t and t[0] != "description":
                    body.append(t)
                i += 1
            if kind == "network":
                groups.net_objects[name] = body
            elif kind == "service":
                groups.svc_objects[name] = body
            continue
        rest.append((i + 1, stripped))
        i += 1
    return groups, rest


def _host_triple(tok: str) -> tuple[int, int, int]:
    """``host`` operand -> (family, lo, hi); family by v6 colon literal."""
    if ":" in tok:
        a = ip6_to_int(tok)
        return (FAM_V6, a, a)
    a = ip_to_u32(tok)
    return (FAM_V4, a, a)


def _resolve_network_group(
    groups: _Groups, name: str, _seen=None
) -> list[tuple[int, int, int]]:
    if _seen is None:
        _seen = set()
    if name in _seen:
        raise AclParseError(f"object-group cycle via {name!r}")
    if name not in groups.network:
        raise AclParseError(f"unknown network object-group {name!r}")
    _seen.add(name)
    out: list[tuple[int, int, int]] = []
    for toks in groups.network[name]:
        if toks[0] == "network-object":
            if toks[1] == "host":
                out.append(_host_triple(toks[2]))
            elif toks[1] == "object":
                out.extend(_resolve_network_object(groups, toks[2]))
            elif ":" in toks[1]:
                # v6 members are spelled as a single prefix token
                out.append((FAM_V6, *prefix6_range(toks[1])))
            else:
                out.append((FAM_V4, *subnet_range(toks[1], toks[2])))
        elif toks[0] == "group-object":
            out.extend(_resolve_network_group(groups, toks[1], _seen))
        else:
            raise AclParseError(f"unsupported network-group member: {' '.join(toks)!r}")
    _seen.discard(name)
    return out


def _resolve_network_object(groups: _Groups, name: str) -> list[tuple[int, int, int]]:
    if name not in groups.net_objects:
        raise AclParseError(f"unknown network object {name!r}")
    out = []
    for toks in groups.net_objects[name]:
        if toks[0] == "host":
            out.append(_host_triple(toks[1]))
        elif toks[0] == "subnet":
            if ":" in toks[1]:
                # v6 subnets are one prefix token (``subnet 2001:db8::/64``)
                out.append((FAM_V6, *prefix6_range(toks[1])))
            else:
                out.append((FAM_V4, *subnet_range(toks[1], toks[2])))
        elif toks[0] == "range":
            if ":" in toks[1] or ":" in toks[2]:
                lo, hi = ip6_to_int(toks[1]), ip6_to_int(toks[2])
                fam = FAM_V6
            else:
                lo, hi = ip_to_u32(toks[1]), ip_to_u32(toks[2])
                fam = FAM_V4
            if lo > hi:
                # real ASA rejects inverted ranges; the device kernel's
                # wraparound range check also requires lo <= hi
                raise AclParseError(
                    f"inverted address range {toks[1]}-{toks[2]} in network "
                    f"object {name!r}"
                )
            out.append((fam, lo, hi))
        elif toks[0] in ("nat", "fqdn"):
            continue  # not matchable statically
        else:
            raise AclParseError(f"unsupported network-object member: {' '.join(toks)!r}")
    if not out:
        raise AclParseError(f"network object {name!r} has no address definition")
    return out


def _port_spec_from_tokens(toks: list[str], pos: int) -> tuple[list[tuple[int, int]], int]:
    """Parse ``eq p | range a b | gt p | lt p | neq p`` at toks[pos].

    Returns (ranges, new_pos).  ``neq`` yields two ranges — the caller's
    cross-product expansion turns that into two rows, matching first-match
    semantics because both rows carry the same configured-rule id.
    """
    op = toks[pos]
    if op == "eq":
        v = _port_value(toks[pos + 1])
        return [(v, v)], pos + 2
    if op == "range":
        lo, hi = _port_value(toks[pos + 1]), _port_value(toks[pos + 2])
        if lo > hi:
            # real ASA rejects inverted port ranges; the device kernel's
            # wraparound range check also requires lo <= hi
            raise AclParseError(f"inverted port range {lo}-{hi}")
        return [(lo, hi)], pos + 3
    if op == "gt":
        v = _port_value(toks[pos + 1])
        return ([(v + 1, PORT_MAX)] if v < PORT_MAX else []), pos + 2
    if op == "lt":
        v = _port_value(toks[pos + 1])
        return ([(0, v - 1)] if v > 0 else []), pos + 2
    if op == "neq":
        v = _port_value(toks[pos + 1])
        rs = []
        if v > 0:
            rs.append((0, v - 1))
        if v < PORT_MAX:
            rs.append((v + 1, PORT_MAX))
        return rs, pos + 2
    raise AclParseError(f"bad port operator {op!r}")


def _resolve_service_group_ports(groups: _Groups, name: str, _seen=None) -> list[tuple[int, int]]:
    """Ports of a proto-typed service group (``object-group service NAME tcp``)."""
    if _seen is None:
        _seen = set()
    if name in _seen:
        raise AclParseError(f"object-group cycle via {name!r}")
    g = groups.service.get(name)
    if g is None:
        raise AclParseError(f"unknown service object-group {name!r}")
    _seen.add(name)
    out: list[tuple[int, int]] = []
    for toks in g["body"]:
        if toks[0] == "port-object":
            rs, _ = _port_spec_from_tokens(toks, 1)
            out.extend(rs)
        elif toks[0] == "group-object":
            out.extend(_resolve_service_group_ports(groups, toks[1], _seen))
        else:
            raise AclParseError(f"unsupported service-group member: {' '.join(toks)!r}")
    _seen.discard(name)
    return out


@dataclasses.dataclass(frozen=True)
class _ProtoAlt:
    """One protocol alternative, optionally bundling port constraints.

    Generic ``object-group service`` groups (no proto suffix) contain
    ``service-object tcp destination eq 443`` members that bind protocol and
    ports together; this carries that bundle through expansion.
    """

    proto: tuple[int, int]
    sport: tuple[int, int] | None = None
    dport: tuple[int, int] | None = None


def _parse_service_object_member(toks: list[str]) -> list[_ProtoAlt]:
    # service-object <proto> [source OP ...] [destination OP ...]
    # service-object object NAME is resolved by the caller.
    proto_tok = toks[1]
    if proto_tok == "icmp":
        protos = [(1, 1)]
    else:
        protos = _proto_ranges(proto_tok)
    sports: list[tuple[int, int]] = [FULL_PORTS]
    dports: list[tuple[int, int]] = [FULL_PORTS]
    pos = 2
    while pos < len(toks):
        if toks[pos] == "source":
            sports, pos = _port_spec_from_tokens(toks, pos + 1)
        elif toks[pos] == "destination":
            dports, pos = _port_spec_from_tokens(toks, pos + 1)
        else:
            pos += 1  # icmp type etc. — not constrained here
    return [
        _ProtoAlt(p, sp, dp)
        for p in protos
        for sp in sports
        for dp in dports
    ]


def _resolve_generic_service_group(groups: _Groups, name: str, _seen=None) -> list[_ProtoAlt]:
    if _seen is None:
        _seen = set()
    if name in _seen:
        raise AclParseError(f"object-group cycle via {name!r}")
    g = groups.service.get(name)
    if g is None:
        raise AclParseError(f"unknown service object-group {name!r}")
    _seen.add(name)
    out: list[_ProtoAlt] = []
    for toks in g["body"]:
        if toks[0] == "service-object":
            if toks[1] == "object":
                out.extend(_resolve_service_object(groups, toks[2]))
            else:
                out.extend(_parse_service_object_member(toks))
        elif toks[0] == "group-object":
            out.extend(_resolve_generic_service_group(groups, toks[1], _seen))
        elif toks[0] == "port-object":
            # proto-typed group referenced generically
            proto = g["proto"]
            rs, _ = _port_spec_from_tokens(toks, 1)
            for pr in _proto_alts_for_typed(proto):
                out.extend(_ProtoAlt(pr, None, r) for r in rs)
        else:
            raise AclParseError(f"unsupported service-group member: {' '.join(toks)!r}")
    _seen.discard(name)
    return out


def _resolve_service_object(groups: _Groups, name: str) -> list[_ProtoAlt]:
    if name not in groups.svc_objects:
        raise AclParseError(f"unknown service object {name!r}")
    out = []
    for toks in groups.svc_objects[name]:
        if toks[0] == "service":
            out.extend(_parse_service_object_member(["service-object", *toks[1:]]))
    if not out:
        raise AclParseError(f"service object {name!r} has no service definition")
    return out


def _proto_alts_for_typed(proto: str | None) -> list[tuple[int, int]]:
    if proto == "tcp":
        return [(6, 6)]
    if proto == "udp":
        return [(17, 17)]
    if proto == "tcp-udp":
        return [(6, 6), (17, 17)]
    raise AclParseError(f"service group without usable protocol type: {proto!r}")


def _resolve_protocol_group(groups: _Groups, name: str, _seen=None) -> list[tuple[int, int]]:
    if _seen is None:
        _seen = set()
    if name in _seen:
        raise AclParseError(f"object-group cycle via {name!r}")
    if name not in groups.protocol:
        raise AclParseError(f"unknown protocol object-group {name!r}")
    _seen.add(name)
    out = []
    for toks in groups.protocol[name]:
        if toks[0] == "protocol-object":
            out.extend(_proto_ranges(toks[1]))
        elif toks[0] == "group-object":
            out.extend(_resolve_protocol_group(groups, toks[1], _seen))
        else:
            raise AclParseError(f"unsupported protocol-group member: {' '.join(toks)!r}")
    _seen.discard(name)
    return out


def _resolve_icmp_type_group(
    groups: _Groups, name: str, _seen=None,
    type_names: dict | None = None,
) -> list[tuple[int, int]]:
    """Resolve an icmp-type group; names resolve through ``type_names``
    (the referencing ACE's family table — ICMPv6 numbers differ from
    their v4 namesakes, so an icmp6 ACE must pass ICMP6_TYPE_NAMES)."""
    if type_names is None:
        type_names = ICMP_TYPE_NAMES
    if _seen is None:
        _seen = set()
    if name in _seen:
        raise AclParseError(f"object-group cycle via {name!r}")
    if name not in groups.icmp_type:
        raise AclParseError(f"unknown icmp-type object-group {name!r}")
    _seen.add(name)
    out = []
    for toks in groups.icmp_type[name]:
        if toks[0] == "icmp-object":
            t = type_names.get(toks[1])
            if t is None:
                try:
                    t = int(toks[1])
                except ValueError:
                    raise AclParseError(f"unknown icmp type {toks[1]!r}") from None
            out.append((t, t))
        elif toks[0] == "group-object":
            out.extend(_resolve_icmp_type_group(groups, toks[1], _seen, type_names))
        else:
            raise AclParseError(f"unsupported icmp-type member: {' '.join(toks)!r}")
    _seen.discard(name)
    return out


# ---------------------------------------------------------------------------
# ACE parsing
# ---------------------------------------------------------------------------

_ADDR_STARTERS = {"any", "any4", "any6", "host", "object-group", "object", "interface"}
_PORT_OPS = {"eq", "range", "gt", "lt", "neq"}
_TRAILERS = {"log", "inactive", "time-range"}


def _parse_address(
    groups: _Groups, toks: list[str], pos: int
) -> tuple[list[tuple[int, int, int]], int]:
    """Address spec at toks[pos] -> ((family, lo, hi) alternatives, new pos).

    ``any`` yields the family wildcard (resolved per ruleset by
    parse_asa_config); ``any4``/``any6`` pin a family; v6 operands are
    recognised by their colon literals.
    """
    t = toks[pos]
    if t == "any":
        return [ANY_WILD], pos + 1
    if t == "any4":
        return [ANY4], pos + 1
    if t == "any6":
        return [ANY6], pos + 1
    if t == "host":
        return [_host_triple(toks[pos + 1])], pos + 2
    if t == "object-group":
        return _resolve_network_group(groups, toks[pos + 1]), pos + 2
    if t == "object":
        return _resolve_network_object(groups, toks[pos + 1]), pos + 2
    if t == "interface":
        # matches traffic to/from the interface address; not statically
        # resolvable here — treat as v4-any, as the reference's coarse
        # parse does (v4-era construct; a v6 deployment would use any6)
        return [ANY4], pos + 2
    if ":" in t:
        # v6 network operand: one prefix token (``2001:db8::/64``)
        return [(FAM_V6, *prefix6_range(t))], pos + 1
    # plain "NET MASK"
    return [(FAM_V4, *subnet_range(t, toks[pos + 1]))], pos + 2


def _maybe_port_spec(
    groups: _Groups, toks: list[str], pos: int
) -> tuple[list[tuple[int, int]] | None, int]:
    """Port spec at toks[pos], or None if the next token starts an address."""
    if pos >= len(toks):
        return None, pos
    t = toks[pos]
    if t in _PORT_OPS:
        return _port_spec_from_tokens(toks, pos)
    if t == "object-group" and pos + 1 < len(toks):
        name = toks[pos + 1]
        # service group here = port spec; network group = next address
        if name in groups.service:
            g = groups.service[name]
            if g["proto"] in ("tcp", "udp", "tcp-udp"):
                return _resolve_service_group_ports(groups, name), pos + 2
        return None, pos
    return None, pos


def parse_ace_line(
    groups: _Groups, acl: str, index: int, line: str, toks: list[str]
) -> AclRule:
    """Parse one ``access-list NAME extended permit|deny ...`` line."""
    rule = AclRule(acl=acl, index=index, text=line)
    # toks: access-list NAME [extended] permit|deny PROTO SRC [SPORT] DST [DPORT] ...
    pos = 2
    if toks[pos] == "extended":
        pos += 1
    action_tok = toks[pos]
    if action_tok not in ("permit", "deny"):
        raise AclParseError(f"bad action {action_tok!r} in: {line!r}")
    action = PERMIT if action_tok == "permit" else DENY
    pos += 1

    # protocol spec
    ptok = toks[pos]
    proto_alts: list[_ProtoAlt]
    generic_service = False
    if ptok == "object-group":
        name = toks[pos + 1]
        if name in groups.protocol:
            proto_alts = [_ProtoAlt(p) for p in _resolve_protocol_group(groups, name)]
        elif name in groups.service:
            proto_alts = _resolve_generic_service_group(groups, name)
            generic_service = True
        else:
            raise AclParseError(f"unknown protocol/service group {name!r} in: {line!r}")
        pos += 2
    elif ptok == "object":
        proto_alts = _resolve_service_object(groups, toks[pos + 1])
        generic_service = True
        pos += 2
    else:
        proto_alts = [_ProtoAlt(p) for p in _proto_ranges(ptok)]
        pos += 1

    src, pos = _parse_address(groups, toks, pos)
    sports, pos = _maybe_port_spec(groups, toks, pos)
    dst, pos = _parse_address(groups, toks, pos)
    dports, pos = _maybe_port_spec(groups, toks, pos)

    icmp_types: list[tuple[int, int]] | None = None
    is_icmp = any(a.proto == (1, 1) for a in proto_alts) or ptok in ("icmp", "icmp6")
    # named types resolve per family: ICMPv6 numbers differ from their v4
    # namesakes (echo-reply is 129, not 0)
    type_names = ICMP6_TYPE_NAMES if ptok == "icmp6" else ICMP_TYPE_NAMES
    if dports is None and is_icmp and pos < len(toks) and toks[pos] not in _TRAILERS:
        t = toks[pos]
        if t == "object-group" and pos + 1 < len(toks) and toks[pos + 1] in groups.icmp_type:
            icmp_types = _resolve_icmp_type_group(
                groups, toks[pos + 1], type_names=type_names
            )
            pos += 2
        elif t in type_names:
            v = type_names[t]
            icmp_types = [(v, v)]
            pos += 1
        elif t.isdigit():
            v = int(t)
            icmp_types = [(v, v)]
            pos += 1
    # trailing keywords (log, inactive, time-range) — "inactive" disables the ACE
    if "inactive" in toks[pos:]:
        return rule  # configured but disabled: zero expanded rows, still reported

    # NB: an empty range list ([] from e.g. "gt 65535") means the spec can
    # never match — distinct from None (no spec -> full range).
    n_pairs = 0
    for alt in proto_alts:
        if generic_service and alt.sport:
            alt_sports = [alt.sport]
        else:
            alt_sports = sports if sports is not None else [FULL_PORTS]
        if generic_service and alt.dport:
            alt_dports = [alt.dport]
        elif icmp_types is not None and alt.proto in ((1, 1), (58, 58)):
            # ICMP types ride the dport column for icmp AND icmp6
            alt_dports = icmp_types
        else:
            alt_dports = dports if dports is not None else [FULL_PORTS]
        for s in src:
            for d in dst:
                sf, df = s[0], d[0]
                if sf != FAM_WILD and df != FAM_WILD and sf != df:
                    continue  # a cross-family pair can match no packet
                fam = sf or df  # FAM_WILD only when both sides are wild
                full = FULL_ADDR6 if fam == FAM_V6 else FULL_ADDR
                slo, shi = (s[1], s[2]) if sf != FAM_WILD else full
                dlo, dhi = (d[1], d[2]) if df != FAM_WILD else full
                n_pairs += 1
                for sp in alt_sports:
                    for dp in alt_dports:
                        rule.aces.append(
                            Ace(
                                action=action,
                                proto_lo=alt.proto[0],
                                proto_hi=alt.proto[1],
                                src_lo=slo,
                                src_hi=shi,
                                sport_lo=sp[0],
                                sport_hi=sp[1],
                                dst_lo=dlo,
                                dst_hi=dhi,
                                dport_lo=dp[0],
                                dport_hi=dp[1],
                                family=fam,
                            )
                        )
    if src and dst and proto_alts and n_pairs == 0:
        # every src/dst pairing crossed families (e.g. ``any4`` source
        # with a v6-only destination group) — real ASA rejects such
        # entries; an unmatched-forever rule would silently distort the
        # unused-rule report
        raise AclParseError(f"no same-family src/dst combination in: {line!r}")
    return rule


_STANDARD_RE = re.compile(r"^access-list\s+(\S+)\s+standard\s+(permit|deny)\s+(.*)$")


def parse_asa_config(text: str, firewall: str, strict: bool = True) -> Ruleset:
    """Parse one firewall's ASA configuration into a :class:`Ruleset`.

    ``strict=True`` (default) raises :class:`AclParseError` on any
    unsupported construct.  ``strict=False`` is the ops-tool mode: an
    unsupported access-list entry (IPv6, exotic object members, ...) is
    skipped and recorded in ``Ruleset.skipped`` — it still consumes its
    rule index so later rules keep their device-side positions — and the
    IPv4 analysis proceeds.
    """
    lines = text.splitlines()
    groups, rest = _collect_blocks(lines)
    rs = Ruleset(firewall=firewall)
    indices: dict[str, int] = {}

    for lineno, line in rest:
        toks = line.split()
        if not toks:
            continue
        if toks[0] == "access-group":
            # access-group NAME in|out interface IFNAME
            if len(toks) >= 5 and toks[3] == "interface" and toks[2] in ("in", "out"):
                rs.bindings[(toks[4], toks[2])] = toks[1]
            continue
        if toks[0] != "access-list" or len(toks) < 3:
            continue
        acl = toks[1]
        if toks[2] == "remark":
            continue
        m = _STANDARD_RE.match(line)
        try:
            if m:
                # standard ACL: source-address-only match
                acl, action_tok, addr = m.groups()
                indices[acl] = indices.get(acl, 0) + 1
                rule = AclRule(acl=acl, index=indices[acl], text=line)
                atoks = addr.split()
                if atoks[0] in ("any", "any4"):
                    ranges = [FULL_ADDR]
                elif atoks[0] == "host":
                    a = ip_to_u32(atoks[1])
                    ranges = [(a, a)]
                else:
                    ranges = [subnet_range(atoks[0], atoks[1])]
                action = PERMIT if action_tok == "permit" else DENY
                for lo, hi in ranges:
                    rule.aces.append(
                        Ace(action, *FULL_PROTO, lo, hi, *FULL_PORTS, *FULL_ADDR, *FULL_PORTS)
                    )
                rs.acls.setdefault(acl, []).append(rule)
                continue
            indices[acl] = indices.get(acl, 0) + 1
            rule = parse_ace_line(groups, acl, indices[acl], line, toks)
        except IndexError:
            # truncated entry (either branch); same skip/raise policy
            err = AclParseError(f"truncated access-list entry: {line!r}")
            if strict:
                raise err from None
            rs.acls.setdefault(acl, [])
            rs.skipped.append((lineno, str(err), line))
            continue
        except AclParseError as e:
            if strict:
                raise
            # the index was consumed above; ensure the ACL exists so its
            # implicit deny / bindings still resolve even if every entry
            # was skipped
            rs.acls.setdefault(acl, [])
            rs.skipped.append((lineno, str(e), line))
            continue
        rs.acls.setdefault(acl, []).append(rule)
    _resolve_wildcard_families(rs)
    return rs


def _resolve_wildcard_families(rs: Ruleset) -> None:
    """Resolve FAM_WILD aces (``any`` src AND dst) per ruleset.

    Pure-v4 configs: the wildcard is v4-only — every pre-v6 corpus keeps
    its exact historical expansion (row counts, tensors, reports all
    bit-identical).  Configs with explicit v6 content (a colon literal or
    ``any6`` anywhere): ``any`` means both families, the ASA 9.0+
    unified-ACL semantic, so ``permit ip any any`` really does cover v6
    traffic there.  The v6 twin sits next to its v4 ace — same configured
    rule, same key — so rule-level counts are unaffected by the order.
    """
    has_v6 = any(
        a.family == FAM_V6
        for rules in rs.acls.values()
        for r in rules
        for a in r.aces
    )
    for rules in rs.acls.values():
        for r in rules:
            if all(a.family != FAM_WILD for a in r.aces):
                continue
            new: list[Ace] = []
            for a in r.aces:
                if a.family != FAM_WILD:
                    new.append(a)
                    continue
                new.append(dataclasses.replace(a, family=FAM_V4))
                if has_v6:
                    new.append(
                        dataclasses.replace(
                            a,
                            family=FAM_V6,
                            src_lo=0,
                            src_hi=U128_MAX,
                            dst_lo=0,
                            dst_hi=U128_MAX,
                        )
                    )
            r.aces = new


def parse_config_file(path: str, firewall: str | None = None, strict: bool = True) -> Ruleset:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = f.read()
    if firewall is None:
        m = re.search(r"^hostname\s+(\S+)", text, re.MULTILINE)
        firewall = m.group(1) if m else path.rsplit("/", 1)[-1]
    return parse_asa_config(text, firewall, strict=strict)
