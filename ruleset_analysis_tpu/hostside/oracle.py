"""The exact oracle: reference mapper/reducer semantics in pure Python.

This is a direct, trivially-auditable implementation of the reference's hot
path (SURVEY.md §4.3/§4.4): per log line, linear first-match scan over the
named ACL's expanded ACEs in configuration order; per matched configured
rule, an exact hit count.  It is deliberately written against the *parsed*
:class:`Ruleset` objects — NOT the packed tensors — so it is an independent
yardstick for the TPU path (SURVEY.md §5 "golden semantics tests") and the
stand-in for the reference's exact Hadoop run when measuring unused-rule
recall.

It also computes the exact versions of every sketched statistic:
per-rule unique-source cardinality and per-ACL top talkers.
"""

from __future__ import annotations

import dataclasses
from collections import Counter, defaultdict
from collections.abc import Iterable

from .aclparse import Ruleset
from .syslog import ParsedLine, parse_line

#: Key identifying one configured rule: (firewall, acl, 1-based rule index).
#: Index 0 means the ACL's implicit deny.
RuleKey = tuple[str, str, int]


@dataclasses.dataclass
class OracleResult:
    """Exact analysis results (the reduce output + report inputs)."""

    hits: Counter  # RuleKey -> exact hit count
    sources: dict  # RuleKey -> set of src IPs (exact cardinality)
    talkers: dict  # (firewall, acl) -> Counter of src IPs
    lines_total: int = 0
    lines_matched: int = 0
    lines_skipped: int = 0

    def unused_rules(self, rulesets: Iterable[Ruleset]) -> list[RuleKey]:
        """Configured rules with zero hits, in configuration order."""
        out = []
        for rs in rulesets:
            for acl, rules in rs.acls.items():
                for rule in rules:
                    key = (rs.firewall, acl, rule.index)
                    if self.hits.get(key, 0) == 0:
                        out.append(key)
        return out


class Oracle:
    """Streaming exact analyzer over parsed rulesets."""

    def __init__(self, rulesets: list[Ruleset]):
        self.by_fw = {rs.firewall: rs for rs in rulesets}
        self.rulesets = rulesets
        self.result = OracleResult(
            hits=Counter(), sources=defaultdict(set), talkers=defaultdict(Counter)
        )

    def resolve_acl(self, p: ParsedLine) -> tuple[Ruleset, str] | None:
        rs = self.by_fw.get(p.firewall)
        if rs is None:
            return None
        if p.acl is not None:
            return (rs, p.acl) if p.acl in rs.acls else None
        if p.ingress_if is not None:
            bound = rs.bindings.get(p.ingress_if)
            if bound and bound[1] == "in" and bound[0] in rs.acls:
                return rs, bound[0]
        return None

    def match_line(self, p: ParsedLine) -> RuleKey | None:
        """First-match key for one parsed line (None = line not analyzable)."""
        resolved = self.resolve_acl(p)
        if resolved is None:
            return None
        rs, acl = resolved
        for rule in rs.acls[acl]:
            for ace in rule.aces:
                if ace.matches(p.proto, p.src, p.sport, p.dst, p.dport):
                    return (rs.firewall, acl, rule.index)
        return (rs.firewall, acl, 0)  # implicit deny

    def consume(self, lines: Iterable[str]) -> OracleResult:
        r = self.result
        for line in lines:
            r.lines_total += 1
            p = parse_line(line)
            key = None if p is None else self.match_line(p)
            if key is None:
                r.lines_skipped += 1
                continue
            r.lines_matched += 1
            r.hits[key] += 1
            r.sources[key].add(p.src)
            r.talkers[(key[0], key[1])][p.src] += 1
        return r

    def consume_parsed(self, parsed: Iterable[ParsedLine]) -> OracleResult:
        r = self.result
        for p in parsed:
            r.lines_total += 1
            key = self.match_line(p)
            if key is None:
                r.lines_skipped += 1
                continue
            r.lines_matched += 1
            r.hits[key] += 1
            r.sources[key].add(p.src)
            r.talkers[(key[0], key[1])][p.src] += 1
        return r


def unused_rule_recall(exact_unused: list[RuleKey], estimated_unused: list[RuleKey]) -> float:
    """Fraction of the exact run's unused rules the estimated run also found.

    This is the headline accuracy metric (BASELINE.md: >=99% unused-ACL
    recall vs the exact run).
    """
    if not exact_unused:
        return 1.0
    est = set(estimated_unused)
    return sum(1 for k in exact_unused if k in est) / len(exact_unused)
