"""The exact oracle: reference mapper/reducer semantics in pure Python.

This is a direct, trivially-auditable implementation of the reference's hot
path (SURVEY.md §4.3/§4.4): per log line, linear first-match scan over the
named ACL's expanded ACEs in configuration order; per matched configured
rule, an exact hit count.  It is deliberately written against the *parsed*
:class:`Ruleset` objects — NOT the packed tensors — so it is an independent
yardstick for the TPU path (SURVEY.md §5 "golden semantics tests") and the
stand-in for the reference's exact Hadoop run when measuring unused-rule
recall.

It also computes the exact versions of every sketched statistic:
per-rule unique-source cardinality and per-ACL top talkers.
"""

from __future__ import annotations

import dataclasses
from collections import Counter, defaultdict
from collections.abc import Iterable

from .aclparse import Ruleset
from .syslog import ParsedLine, parse_line

#: Key identifying one configured rule: (firewall, acl, 1-based rule index).
#: Index 0 means the ACL's implicit deny.
RuleKey = tuple[str, str, int]


@dataclasses.dataclass
class OracleResult:
    """Exact analysis results (the reduce output + report inputs)."""

    hits: Counter  # RuleKey -> exact hit count
    sources: dict  # RuleKey -> set of src IPs (exact cardinality)
    talkers: dict  # (firewall, acl) -> Counter of src IPs
    lines_total: int = 0
    lines_matched: int = 0
    lines_skipped: int = 0

    def unused_rules(self, rulesets: Iterable[Ruleset]) -> list[RuleKey]:
        """Configured rules with zero hits, in configuration order."""
        out = []
        for rs in rulesets:
            for acl, rules in rs.acls.items():
                for rule in rules:
                    key = (rs.firewall, acl, rule.index)
                    if self.hits.get(key, 0) == 0:
                        out.append(key)
        return out


class Oracle:
    """Streaming exact analyzer over parsed rulesets."""

    def __init__(self, rulesets: list[Ruleset]):
        self.by_fw = {rs.firewall: rs for rs in rulesets}
        self.rulesets = rulesets
        self.result = OracleResult(
            hits=Counter(), sources=defaultdict(set), talkers=defaultdict(Counter)
        )

    def resolve_acls(self, p: ParsedLine) -> list[tuple[Ruleset, str]]:
        """Every ACL this line is evaluated against (possibly two).

        A connection message is filtered by the ingress interface's ``in``
        ACL and, independently, by the egress interface's ``out`` ACL —
        one evaluation each, exactly like LinePacker.resolve_gids.
        """
        rs = self.by_fw.get(p.firewall)
        if rs is None:
            return []
        if p.acl is not None:
            return [(rs, p.acl)] if p.acl in rs.acls else []
        out: list[tuple[Ruleset, str]] = []
        if p.ingress_if is not None:
            acl = rs.bindings.get((p.ingress_if, "in"))
            if acl is not None and acl in rs.acls:
                out.append((rs, acl))
        if p.egress_if is not None:
            acl = rs.bindings.get((p.egress_if, "out"))
            if acl is not None and acl in rs.acls:
                out.append((rs, acl))
        return out

    def resolve_acl(self, p: ParsedLine) -> tuple[Ruleset, str] | None:
        """First resolved ACL (compatibility helper; prefer resolve_acls)."""
        acls = self.resolve_acls(p)
        return acls[0] if acls else None

    def _match_one(self, rs: Ruleset, acl: str, p: ParsedLine) -> RuleKey:
        for rule in rs.acls[acl]:
            for ace in rule.aces:
                if ace.matches(p.proto, p.src, p.sport, p.dst, p.dport, p.family):
                    return (rs.firewall, acl, rule.index)
        return (rs.firewall, acl, 0)  # implicit deny

    def match_keys(self, p: ParsedLine) -> list[RuleKey]:
        """First-match key per resolved ACL evaluation (empty = skipped)."""
        return [self._match_one(rs, acl, p) for rs, acl in self.resolve_acls(p)]

    def match_line(self, p: ParsedLine) -> RuleKey | None:
        """First evaluation's key (compatibility helper; prefer match_keys)."""
        keys = self.match_keys(p)
        return keys[0] if keys else None

    def _fold(self, p: ParsedLine | None) -> None:
        r = self.result
        r.lines_total += 1
        keys = [] if p is None else self.match_keys(p)
        if not keys:
            r.lines_skipped += 1
            return
        # lines_matched counts ACL evaluations (a dual-bound connection
        # line contributes two), matching the packers' `parsed` counter.
        # Source identity is (family, address): a v4 address and a v6
        # address with equal low bits (10.0.0.1 vs ::a00:1) are DISTINCT
        # sources and must not merge in exact sets/counters.
        for key in keys:
            r.lines_matched += 1
            r.hits[key] += 1
            r.sources[key].add((p.family, p.src))
            r.talkers[(key[0], key[1])][(p.family, p.src)] += 1

    def consume(self, lines: Iterable[str]) -> OracleResult:
        for line in lines:
            self._fold(parse_line(line))
        return self.result

    def consume_parsed(self, parsed: Iterable[ParsedLine]) -> OracleResult:
        for p in parsed:
            self._fold(p)
        return self.result


def unused_rule_recall(exact_unused: list[RuleKey], estimated_unused: list[RuleKey]) -> float:
    """Fraction of the exact run's unused rules the estimated run also found.

    This is the headline accuracy metric (BASELINE.md: >=99% unused-ACL
    recall vs the exact run).
    """
    if not exact_unused:
        return 1.0
    est = set(estimated_unused)
    return sum(1 for k in exact_unused if k in est) / len(exact_unused)
