"""Packing: Rulesets -> device-ready rule tensor; parsed lines -> tuple batches.

This is the rebuilt L1->L3 boundary (SURVEY.md §2): where the reference
pickles per-firewall ACL dicts and ships them to every Hadoop map task, we
pack every firewall's expanded ACEs into ONE flat uint32 rule matrix that
lives in device HBM, plus small host-side lookup tables.

Rule matrix layout (``[R, RULE_COLS] uint32``, row order = global config
order, which is load-bearing for first-match parity):

  col 0  acl_gid   — global ACL id (firewall+ACL resolved on host)
  col 1  proto_lo  | 2 proto_hi
  col 3  src_lo    | 4 src_hi
  col 5  sport_lo  | 6 sport_hi
  col 7  dst_lo    | 8 dst_hi
  col 9  dport_lo  | 10 dport_hi
  col 11 key_id    — id of the configured rule this expanded row belongs to

Padding rows carry ``acl_gid = NO_ACL`` and can never match.

Tuple batch layout (``[B, TUPLE_COLS] uint32``):

  col 0 acl_gid | 1 proto | 2 src | 3 sport | 4 dst | 5 dport | 6 valid

Key space: keys ``0..n_rules-1`` are configured rules in global order;
keys ``n_rules..n_rules+n_acls-1`` are each ACL's implicit deny.  The
unused-rule report is "configured-rule keys with zero hits" (SURVEY.md §4.5).
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from ..errors import AnalysisError
from .aclparse import Ruleset
from .syslog import ParsedLine, parse_line

RULE_COLS = 12
TUPLE_COLS = 7
#: Wire-format columns (see :func:`compact_batch`): the host->device feed
#: is the e2e bottleneck on PCIe-starved links, so batches cross the wire
#: bit-packed at 16 B/line instead of the working layout's 28 B/line.
WIRE_COLS = 4
#: WEIGHTED wire columns: the wire layout plus one trailing uint32
#: weights row (20 B/row).  A coalesced batch ships every distinct
#: evaluation tuple once with its repetition count; the device step
#: reads the weights row as its valid/weight plane (pipeline.batch_cols),
#: so registers update exactly as the uncoalesced batch would.
WIREW_COLS = 5

#: Rule-axis block size for the match kernel's scan path (defined here,
#: jax-free, so host-side packing/stacking and the device kernel share
#: one padding granularity).  Keeps each [B, RULE_BLOCK] predicate tile
#: comfortably inside VMEM at B = 64k.
RULE_BLOCK = 512

# rule matrix columns
R_ACL, R_PLO, R_PHI, R_SLO, R_SHI, R_SPLO, R_SPHI, R_DLO, R_DHI, R_DPLO, R_DPHI, R_KEY = range(12)
# tuple columns
T_ACL, T_PROTO, T_SRC, T_SPORT, T_DST, T_DPORT, T_VALID = range(7)
# wire columns (compact_batch): src | dst | sport<<16|dport | proto<<24|valid<<23|acl
W_SRC, W_DST, W_PORTS, W_META = range(4)
#: weights row of the WEIGHTED wire layout (coalesced batches)
W_WEIGHT = 4

# ---------------------------------------------------------------------------
# IPv6 family (DESIGN.md "IPv6 position"): 128-bit addresses as 4 uint32
# big-endian limbs.  v6 rows/tuples live in SEPARATE tensors so the v4 hot
# path is untouched; splitting by family preserves first-match order
# because a packet can only match ACEs of its own family (aclparse.Ace).
# Rule keys are shared across families — one report, one key universe.
# ---------------------------------------------------------------------------

RULE6_COLS = 24
TUPLE6_COLS = 13

# v6 rule matrix columns: acl | proto lo/hi | src lo limbs | src hi limbs
# | sport lo/hi | dst lo limbs | dst hi limbs | dport lo/hi | key
R6_ACL = 0
R6_PLO, R6_PHI = 1, 2
R6_SLO = 3   # ..6   (big-endian limbs: col R6_SLO+i is bits 127-32i..96-32i)
R6_SHI = 7   # ..10
R6_SPLO, R6_SPHI = 11, 12
R6_DLO = 13  # ..16
R6_DHI = 17  # ..20
R6_DPLO, R6_DPHI = 21, 22
R6_KEY = 23

# v6 tuple columns
T6_ACL = 0
T6_PROTO = 1
T6_SRC = 2   # ..5
T6_SPORT = 6
T6_DST = 7   # ..10
T6_DPORT = 11
T6_VALID = 12

#: v6 wire columns (DESIGN.md "wire format v2", 40 B/line): the address
#: limbs ride uncompressed, ports pack as sport<<16|dport and meta as
#: proto<<24|valid<<23|acl — the same two packed words as the v4 format,
#: so the device unpack is the same three VPU shifts.
WIRE6_COLS = 10
W6_SRC = 0   # ..3
W6_DST = 4   # ..7
W6_PORTS = 8
W6_META = 9
#: weighted v6 wire layout: WIRE6_COLS plus a trailing weights row
#: (44 B/row; same contract as the v4 WIREW_COLS layout).
WIRE6W_COLS = 11
W6_WEIGHT = 10


def compact_batch6(batch6: np.ndarray) -> np.ndarray:
    """Column-major working v6 batch ``[TUPLE6_COLS, B]`` -> ``[WIRE6_COLS, B]``."""
    u32 = np.uint32
    out = np.empty((WIRE6_COLS, batch6.shape[1]), dtype=u32)
    out[W6_SRC:W6_SRC + 4] = batch6[T6_SRC:T6_SRC + 4]
    out[W6_DST:W6_DST + 4] = batch6[T6_DST:T6_DST + 4]
    out[W6_PORTS] = (batch6[T6_SPORT] << u32(16)) | (batch6[T6_DPORT] & u32(0xFFFF))
    out[W6_META] = (
        (batch6[T6_PROTO] << u32(24))
        | ((batch6[T6_VALID] & u32(1)) << u32(23))
        | (batch6[T6_ACL] & u32(WIRE_MAX_ACLS - 1))
    )
    return out


def expand_batch6(wire6: np.ndarray) -> np.ndarray:
    """Inverse of :func:`compact_batch6` (tests / debugging).

    Accepts the plain ``[WIRE6_COLS, B]`` layout and the weighted
    ``[WIRE6W_COLS, B]`` layout (T6_VALID then carries the weights).
    """
    u32 = np.uint32
    out = np.zeros((TUPLE6_COLS, wire6.shape[1]), dtype=u32)
    meta = wire6[W6_META]
    out[T6_SRC:T6_SRC + 4] = wire6[W6_SRC:W6_SRC + 4]
    out[T6_DST:T6_DST + 4] = wire6[W6_DST:W6_DST + 4]
    out[T6_SPORT] = wire6[W6_PORTS] >> u32(16)
    out[T6_DPORT] = wire6[W6_PORTS] & u32(0xFFFF)
    out[T6_PROTO] = meta >> u32(24)
    if wire6.shape[0] == WIRE6W_COLS:
        out[T6_VALID] = wire6[W6_WEIGHT]
    else:
        out[T6_VALID] = (meta >> u32(23)) & u32(1)
    out[T6_ACL] = meta & u32(WIRE_MAX_ACLS - 1)
    return out


def u128_limbs(v: int) -> tuple[int, int, int, int]:
    """128-bit int -> 4 big-endian uint32 limbs."""
    m = 0xFFFFFFFF
    return ((v >> 96) & m, (v >> 64) & m, (v >> 32) & m, v & m)


def limbs_u128(l0: int, l1: int, l2: int, l3: int) -> int:
    return (int(l0) << 96) | (int(l1) << 64) | (int(l2) << 32) | int(l3)


#: v6 talker digest->address map size cap (~6 MB of host dict at the
#: cap); past it new v6 sources keep full analysis fidelity but render
#: as raw ``v6#`` digests in the talker section.  One knob for every
#: source tier (text / native / feeder / wire).
V6_DIGEST_CAP = 1 << 18


def fold_src32_np(limbs: np.ndarray) -> np.ndarray:
    """Vectorized :func:`fold_src32_host` over ``[4, n]`` uint32 limbs."""
    u32 = np.uint32
    with np.errstate(over="ignore"):
        h = limbs[0] * u32(0x9E3779B1)
        h = (h ^ limbs[1]) * u32(0x85EBCA77)
        h = (h ^ limbs[2]) * u32(0xC2B2AE3D)
        h = (h ^ limbs[3]) * u32(0x27D4EB2F)
    return h ^ (h >> u32(15))


def fold_src32_host(v: int) -> int:
    """Host twin of ops.match6.fold_src32 (the v6 sketch identity).

    Must stay bit-identical to the device fold: the stream driver records
    digest -> address so reports can render v6 talkers as real addresses.
    tests/test_match6.py pins host/device agreement.
    """
    m = 0xFFFFFFFF
    l0, l1, l2, l3 = u128_limbs(v)
    h = (l0 * 0x9E3779B1) & m
    h = ((h ^ l1) * 0x85EBCA77) & m
    h = ((h ^ l2) * 0xC2B2AE3D) & m
    h = ((h ^ l3) * 0x27D4EB2F) & m
    return h ^ (h >> 15)

#: acl gid budget in the wire meta word: 23 bits (proto takes 8, valid 1).
WIRE_MAX_ACLS = 1 << 23

NO_ACL = np.uint32(0xFFFFFFFF)


@dataclasses.dataclass
class KeyMeta:
    """Report-facing identity of one count key."""

    firewall: str
    acl: str
    index: int  # 1-based rule position; 0 for the ACL's implicit deny
    text: str
    implicit_deny: bool = False
    #: PERMIT(1)/DENY(0) of the configured entry, or -1 when unknown (a
    #: packed artifact written before the static-analysis plane).  The
    #: action never affects matching/counting — only the analyzer's
    #: redundant-vs-conflict split reads it, and it degrades to the
    #: action-free "shadowed" verdict on -1.
    action: int = -1


@dataclasses.dataclass
class PackedRuleset:
    """The packed, device-shippable form of one or more firewalls' rulesets."""

    rules: np.ndarray  # [R, RULE_COLS] uint32
    n_rules: int  # number of configured-rule keys
    n_acls: int
    key_meta: list[KeyMeta]  # len == n_keys
    acl_gid: dict[tuple[str, str], int]  # (firewall, acl name) -> gid
    deny_key: np.ndarray  # [n_acls] uint32: acl_gid -> implicit-deny key
    bindings: dict[tuple[str, str], int]  # (firewall, iface) -> acl_gid ('in')
    #: (firewall, iface) -> acl_gid for ``out``-direction access-groups;
    #: connection messages are evaluated against the egress interface's
    #: out ACL in addition to the ingress in ACL.
    bindings_out: dict[tuple[str, str], int] = dataclasses.field(default_factory=dict)
    #: Lenient-parse skips carried from the Rulesets: (firewall, lineno,
    #: reason) per unsupported config entry — surfaced in the analysis
    #: report so a packed ruleset can't silently hide that its source
    #: config wasn't fully parsed.
    parse_skips: list[tuple[str, int, str]] = dataclasses.field(default_factory=list)
    #: [R6, RULE6_COLS] uint32 — the IPv6 ACE rows (4x uint32 address
    #: limbs), sharing the v4 rows' key universe.  Empty ([0, RULE6_COLS])
    #: for pure-v4 rulesets, in which case the device v6 path never runs.
    rules6: np.ndarray | None = None

    def __post_init__(self):
        if self.rules6 is None:
            self.rules6 = np.zeros((0, RULE6_COLS), dtype=np.uint32)

    @property
    def has_v6(self) -> bool:
        return self.rules6.shape[0] > 0

    @property
    def n_keys(self) -> int:
        return self.n_rules + self.n_acls

    def key_name(self, key: int) -> str:
        m = self.key_meta[key]
        tag = "implicit-deny" if m.implicit_deny else str(m.index)
        return f"{m.firewall} {m.acl} {tag}"


def pack_rulesets(rulesets: list[Ruleset], pad_rules_to: int | None = None) -> PackedRuleset:
    """Pack parsed rulesets into the flat rule matrix + key universe."""
    acl_gid: dict[tuple[str, str], int] = {}
    key_meta: list[KeyMeta] = []
    rows: list[list[int]] = []
    bindings: dict[tuple[str, str], int] = {}
    bindings_out: dict[tuple[str, str], int] = {}

    for rs in rulesets:
        for acl in rs.acls:
            acl_gid[(rs.firewall, acl)] = len(acl_gid)
    if len(acl_gid) > WIRE_MAX_ACLS:
        raise ValueError(
            f"{len(acl_gid)} ACLs exceed the wire format's {WIRE_MAX_ACLS} "
            "acl-gid budget (23 bits of the packed meta word)"
        )

    rows6: list[list[int]] = []
    for rs in rulesets:
        for acl, rules in rs.acls.items():
            gid = acl_gid[(rs.firewall, acl)]
            for rule in rules:
                key = len(key_meta)
                key_meta.append(
                    KeyMeta(
                        firewall=rs.firewall, acl=acl, index=rule.index,
                        text=rule.text,
                        # one config line = one action; every ACE agrees
                        action=rule.aces[0].action if rule.aces else -1,
                    )
                )
                for a in rule.aces:
                    if a.family == 6:
                        rows6.append(
                            [
                                gid,
                                a.proto_lo,
                                a.proto_hi,
                                *u128_limbs(a.src_lo),
                                *u128_limbs(a.src_hi),
                                a.sport_lo,
                                a.sport_hi,
                                *u128_limbs(a.dst_lo),
                                *u128_limbs(a.dst_hi),
                                a.dport_lo,
                                a.dport_hi,
                                key,
                            ]
                        )
                        continue
                    rows.append(
                        [
                            gid,
                            a.proto_lo,
                            a.proto_hi,
                            a.src_lo,
                            a.src_hi,
                            a.sport_lo,
                            a.sport_hi,
                            a.dst_lo,
                            a.dst_hi,
                            a.dport_lo,
                            a.dport_hi,
                            key,
                        ]
                    )
        for (iface, direction), acl in rs.bindings.items():
            if (rs.firewall, acl) not in acl_gid:
                continue
            gid = acl_gid[(rs.firewall, acl)]
            if direction == "in":
                bindings[(rs.firewall, iface)] = gid
            else:
                bindings_out[(rs.firewall, iface)] = gid

    n_rules = len(key_meta)
    n_acls = len(acl_gid)
    deny_key = np.zeros(max(n_acls, 1), dtype=np.uint32)
    for (fw, acl), gid in acl_gid.items():
        deny_key[gid] = n_rules + gid
        key_meta.append(
            KeyMeta(
                firewall=fw, acl=acl, index=0, text="<implicit deny>",
                implicit_deny=True, action=0,
            )
        )

    parse_skips = [
        (rs.firewall, lineno, reason)
        for rs in rulesets
        for lineno, reason, _line in rs.skipped
    ]

    r = len(rows)
    pad_to = max(pad_rules_to or 0, r, 1)
    mat = np.full((pad_to, RULE_COLS), 0, dtype=np.uint32)
    mat[:, R_ACL] = NO_ACL
    if rows:
        mat[:r] = np.asarray(rows, dtype=np.uint32)
    mat6 = (
        np.asarray(rows6, dtype=np.uint32)
        if rows6
        else np.zeros((0, RULE6_COLS), dtype=np.uint32)
    )
    return PackedRuleset(
        rules=mat,
        rules6=mat6,
        n_rules=n_rules,
        n_acls=n_acls,
        key_meta=key_meta,
        acl_gid=acl_gid,
        deny_key=deny_key,
        bindings=bindings,
        bindings_out=bindings_out,
        parse_skips=parse_skips,
    )


# ---------------------------------------------------------------------------
# Wire format: the host->device transfer layout.  Host parsing and tests
# work in the 7-column uint32 layout (one field per lane, convenient to
# index); batches cross PCIe / the dev tunnel bit-packed into 4 words per
# line, and the device step unpacks with three shifts on the VPU.  Field
# widths: src/dst 32, sport/dport 16, proto 8, valid 1, acl gid 23
# (WIRE_MAX_ACLS; pack_rulesets refuses larger inventories).
# ---------------------------------------------------------------------------


def compact_batch(batch: np.ndarray) -> np.ndarray:
    """Column-major working batch ``[TUPLE_COLS, B]`` -> wire ``[WIRE_COLS, B]``."""
    u32 = np.uint32
    out = np.empty((WIRE_COLS, batch.shape[1]), dtype=u32)
    out[W_SRC] = batch[T_SRC]
    out[W_DST] = batch[T_DST]
    out[W_PORTS] = (batch[T_SPORT] << u32(16)) | (batch[T_DPORT] & u32(0xFFFF))
    out[W_META] = (
        (batch[T_PROTO] << u32(24))
        | ((batch[T_VALID] & u32(1)) << u32(23))
        | (batch[T_ACL] & u32(WIRE_MAX_ACLS - 1))
    )
    return out


def compact_grouped(grouped: np.ndarray) -> np.ndarray:
    """Grouped ``[G, TUPLE_COLS, lane]`` -> wire ``[G, WIRE_COLS, lane]``."""
    g, _, lane = grouped.shape
    flat = compact_batch(grouped.transpose(1, 0, 2).reshape(TUPLE_COLS, g * lane))
    return flat.reshape(WIRE_COLS, g, lane).transpose(1, 0, 2)


def expand_batch(wire: np.ndarray) -> np.ndarray:
    """Inverse of :func:`compact_batch` (tests / debugging).

    Accepts both the plain ``[WIRE_COLS, B]`` layout and the weighted
    ``[WIREW_COLS, B]`` layout; in the weighted case the tuple batch's
    valid column carries the weights (0 = invalid, as everywhere).
    """
    u32 = np.uint32
    out = np.zeros((TUPLE_COLS, wire.shape[1]), dtype=u32)
    meta = wire[W_META]
    out[T_SRC] = wire[W_SRC]
    out[T_DST] = wire[W_DST]
    out[T_SPORT] = wire[W_PORTS] >> u32(16)
    out[T_DPORT] = wire[W_PORTS] & u32(0xFFFF)
    out[T_PROTO] = meta >> u32(24)
    if wire.shape[0] == WIREW_COLS:
        out[T_VALID] = wire[W_WEIGHT]
    else:
        out[T_VALID] = (meta >> u32(23)) & u32(1)
    out[T_ACL] = meta & u32(WIRE_MAX_ACLS - 1)
    return out


# ---------------------------------------------------------------------------
# Flow coalescing (ISSUE 5): ASA flow logs are massively repetitive — the
# same 5-tuple logs 106100/302013/302015 lines over and over — so a batch
# compacts into (unique row, weight) pairs before it ever reaches the
# device.  Every register update is weight-linear (counts/CMS/talker
# scatter-adds take ``weights=``) or idempotent (HLL max), so the final
# report is bit-identical to the uncoalesced path while the dominant
# batch-sized scatters, H2D bytes, and device rows shrink by the
# compaction ratio.  This is the MapReduce combiner (Dean & Ghemawat,
# OSDI'04) applied to a scatter-bound device step.
#
# Representation: weights ride the batch's valid plane.  Tuple layouts
# carry them in T_VALID/T6_VALID (uint32; 0 = invalid); wire layouts grow
# one trailing weights row (WIREW_COLS/WIRE6W_COLS) because the packed
# meta word has only a single valid bit.  Unique rows are emitted in
# FIRST-OCCURRENCE order, so batch position is monotone in the first
# occurrence index — the candidate table's representative scatter-max
# over positions selects the same pair the raw batch's would (DESIGN §11).
# ---------------------------------------------------------------------------


def _np_coalesce(
    mat: np.ndarray, want_first: bool = False
) -> tuple[np.ndarray, np.ndarray | None]:
    """Pure-numpy coalesce of a ``[rows, B]`` uint32 plane.

    The LAST row is the weight/valid plane: zero-weight columns are
    dropped, the remaining columns group by the other rows' values, and
    each group's weights sum.  Returns ``([rows, U], first_idx[U] | None)``
    with unique columns in first-occurrence order.  Bit-identical to the
    native ``asa_coalesce`` fast path (tests pin it).
    """
    w = mat[-1]
    pos = np.flatnonzero(w)
    if pos.size == 0:
        out = np.zeros((mat.shape[0], 0), dtype=np.uint32)
        return out, (np.zeros(0, dtype=np.int64) if want_first else None)
    keys = np.ascontiguousarray(mat[:-1, pos].T)  # [Nv, rows-1]
    view = keys.view([("", np.uint32)] * keys.shape[1]).ravel()
    _, first, inv = np.unique(view, return_index=True, return_inverse=True)
    # summed weights are exact in float64 up to 2^53 raw lines per batch
    sums = np.bincount(inv, weights=w[pos].astype(np.float64))
    order = np.argsort(first, kind="stable")  # first-occurrence order
    out = np.empty((mat.shape[0], order.size), dtype=np.uint32)
    out[:-1] = keys[first[order]].T
    out[-1] = sums[order].astype(np.uint64).astype(np.uint32)
    return out, (pos[first[order]].astype(np.int64) if want_first else None)


def coalesce_cols(
    mat: np.ndarray, want_first: bool = False
) -> tuple[np.ndarray, np.ndarray | None]:
    """Coalesce a ``[rows, B]`` uint32 plane whose LAST row is the weight.

    Uses the native open-addressing hash (``asa_coalesce`` in
    ``native/asaparse.cpp``) when the library loads, else the numpy
    fallback — outputs are bit-identical.  Composes: feeding an already
    weighted plane merges duplicate keys and sums their weights.
    """
    if mat.dtype != np.uint32 or mat.ndim != 2:
        raise ValueError(f"expected [rows, B] uint32, got {mat.shape} {mat.dtype}")
    from . import fastparse

    native = fastparse.native_coalesce(mat, want_first)
    if native is not None:
        return native
    return _np_coalesce(mat, want_first)


def coalesce_batch(batch: np.ndarray) -> np.ndarray:
    """``[TUPLE_COLS, B]`` -> weighted ``[TUPLE_COLS, U]``, U <= B.

    Input valid column may itself carry weights (composes).  Output rows
    are distinct (acl, proto, src, sport, dst, dport) tuples in
    first-occurrence order with T_VALID = summed weight.
    """
    if batch.shape[0] != TUPLE_COLS:
        raise ValueError(f"expected [TUPLE_COLS, B], got {batch.shape}")
    out, _ = coalesce_cols(np.ascontiguousarray(batch))
    return out


def coalesce_batch6(batch6: np.ndarray) -> np.ndarray:
    """v6 twin of :func:`coalesce_batch` (``[TUPLE6_COLS, B]`` in/out)."""
    if batch6.shape[0] != TUPLE6_COLS:
        raise ValueError(f"expected [TUPLE6_COLS, B], got {batch6.shape}")
    out, _ = coalesce_cols(np.ascontiguousarray(batch6))
    return out


def _wire_weighted_view(wire: np.ndarray, cols: int, meta_row: int) -> np.ndarray:
    """Wire batch -> weighted-wire plane (weights synthesized from the
    valid bit when absent), ready for :func:`coalesce_cols`."""
    if wire.shape[0] == cols + 1:
        return np.ascontiguousarray(wire)
    tmp = np.empty((cols + 1, wire.shape[1]), dtype=np.uint32)
    tmp[:cols] = wire
    tmp[cols] = (wire[meta_row] >> np.uint32(23)) & np.uint32(1)
    return tmp


def coalesce_wire(wire: np.ndarray) -> np.ndarray:
    """``[WIRE_COLS, B]`` (or already-weighted ``[WIREW_COLS, B]``) ->
    weighted wire ``[WIREW_COLS, U]``.

    The 4 packed words of a valid row ARE the flow key (their valid bit
    is identically set), so grouping by the stored words is grouping by
    the evaluation tuple.  Zero (padding) columns drop out via weight 0.
    """
    if wire.shape[0] not in (WIRE_COLS, WIREW_COLS):
        raise ValueError(f"expected [WIRE_COLS(+1), B], got {wire.shape}")
    out, _ = coalesce_cols(_wire_weighted_view(wire, WIRE_COLS, W_META))
    return out


def coalesce_wire6(wire6: np.ndarray) -> np.ndarray:
    """v6 twin of :func:`coalesce_wire` (``[WIRE6_COLS(+1), B]`` in)."""
    if wire6.shape[0] not in (WIRE6_COLS, WIRE6W_COLS):
        raise ValueError(f"expected [WIRE6_COLS(+1), B], got {wire6.shape}")
    out, _ = coalesce_cols(_wire_weighted_view(wire6, WIRE6_COLS, W6_META))
    return out


def pad_weighted(mat: np.ndarray, to: int) -> np.ndarray:
    """Zero-pad a weighted plane's column axis to ``to`` columns.

    Zero columns carry weight 0 (and a clear valid bit for wire metas),
    so padding is masked on device exactly like any invalid row.
    """
    if mat.shape[-1] >= to:
        return mat
    out = np.zeros((*mat.shape[:-1], to), dtype=np.uint32)
    out[..., : mat.shape[-1]] = mat
    return out


def compact_batch_w(batch: np.ndarray) -> np.ndarray:
    """Weighted working batch ``[TUPLE_COLS, B]`` -> ``[WIREW_COLS, B]``.

    The weighted twin of :func:`compact_batch`: T_VALID carries a full
    uint32 weight, which rides the extra weights row; the meta valid bit
    is set iff the weight is nonzero (so weight-agnostic consumers — the
    reader sanity checks, expand_batch — keep working).
    """
    u32 = np.uint32
    out = np.empty((WIREW_COLS, batch.shape[1]), dtype=u32)
    out[W_SRC] = batch[T_SRC]
    out[W_DST] = batch[T_DST]
    out[W_PORTS] = (batch[T_SPORT] << u32(16)) | (batch[T_DPORT] & u32(0xFFFF))
    out[W_META] = (
        (batch[T_PROTO] << u32(24))
        | ((batch[T_VALID] > 0).astype(u32) << u32(23))
        | (batch[T_ACL] & u32(WIRE_MAX_ACLS - 1))
    )
    out[W_WEIGHT] = batch[T_VALID]
    return out


def compact_grouped_w(grouped: np.ndarray) -> np.ndarray:
    """Weighted grouped ``[G, TUPLE_COLS, lane]`` -> ``[G, WIREW_COLS, lane]``."""
    g, _, lane = grouped.shape
    flat = compact_batch_w(
        grouped.transpose(1, 0, 2).reshape(TUPLE_COLS, g * lane)
    )
    return flat.reshape(WIREW_COLS, g, lane).transpose(1, 0, 2)


class LinePacker:
    """Parses raw syslog lines into packed tuple batches against a PackedRuleset.

    Lines that don't parse, reference an unknown firewall/ACL, or (for
    connection messages) hit interfaces with no ``access-group`` binding
    are packed with ``valid=0`` — the mapper analog of silently skipping
    non-matching input lines.

    One line can produce MORE than one tuple: a connection message whose
    ingress interface has an ``in`` ACL and whose egress interface has an
    ``out`` ACL is evaluated against both (each evaluation is its own
    tuple row, exactly as the reference mapper would scan both ACLs).
    ``parsed`` counts evaluations emitted; ``skipped`` counts lines that
    produced none.
    """

    def __init__(self, packed: PackedRuleset):
        self.packed = packed
        self.skipped = 0
        self.parsed = 0

    def resolve_gids(self, p: ParsedLine) -> list[int]:
        """ACL gids this line must be evaluated against (possibly two)."""
        if p.acl is not None:
            gid = self.packed.acl_gid.get((p.firewall, p.acl))
            return [] if gid is None else [gid]
        out: list[int] = []
        if p.ingress_if is not None:
            gid = self.packed.bindings.get((p.firewall, p.ingress_if))
            if gid is not None:
                out.append(gid)
        if p.egress_if is not None:
            gid = self.packed.bindings_out.get((p.firewall, p.egress_if))
            if gid is not None:
                out.append(gid)
        return out

    def resolve_acl(self, p: ParsedLine) -> int | None:
        """First resolved gid (compatibility helper; prefer resolve_gids)."""
        gids = self.resolve_gids(p)
        return gids[0] if gids else None

    def pack_parsed2(
        self,
        parsed: list[ParsedLine | None],
        batch_size: int | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Pack parsed lines into per-family batches.

        Returns ``([B, TUPLE_COLS], [B6, TUPLE6_COLS])`` uint32 batches
        (each padded with valid=0 rows).  The default capacity is one row
        per line — two when any out-direction binding exists, since a
        connection line can then emit two evaluations.  A line's
        evaluations land in its family's batch; both batches share the
        capacity bound (a chunk of N lines can never exceed N (or 2N)
        evaluations across both families combined).
        """
        if batch_size is not None:
            b = batch_size
        else:
            b = (2 if self.packed.bindings_out else 1) * len(parsed)
        out = np.zeros((b, TUPLE_COLS), dtype=np.uint32)
        out6 = np.zeros((b if self.packed.has_v6 else 0, TUPLE6_COLS), dtype=np.uint32)
        i = 0
        i6 = 0
        for p in parsed:
            gids = [] if p is None else self.resolve_gids(p)
            if gids and p.family == 6 and not self.packed.has_v6:
                # a v6 line against a pure-v4 ruleset can only hit the
                # implicit deny; without v6 rows the device path cannot
                # represent it — counted-skip, exactly the pre-v6 behavior
                gids = []
            if not gids:
                self.skipped += 1
                continue
            if i + i6 + len(gids) > b:
                raise ValueError(
                    f"more than batch_size={b} evaluations in chunk; "
                    "feed fewer lines per chunk (each connection line can "
                    "emit two rows when both in and out ACLs are bound)"
                )
            if p.family == 6:
                s = u128_limbs(p.src)
                d = u128_limbs(p.dst)
                for gid in gids:
                    out6[i6] = (gid, p.proto, *s, p.sport, *d, p.dport, 1)
                    i6 += 1
                    self.parsed += 1
            else:
                for gid in gids:
                    out[i] = (gid, p.proto, p.src, p.sport, p.dst, p.dport, 1)
                    i += 1
                    self.parsed += 1
        return out, out6

    def pack_parsed(self, parsed: list[ParsedLine | None], batch_size: int | None = None) -> np.ndarray:
        """v4-only twin of :meth:`pack_parsed2` (the original API).

        Raises :class:`AnalysisError` if any v6 evaluation was packed —
        callers that may see v6 traffic against a v6-capable ruleset must
        use pack_parsed2; silently dropping supported traffic here would
        corrupt the hit counts.
        """
        out, out6 = self.pack_parsed2(parsed, batch_size)
        if out6.size and int(out6[:, T6_VALID].sum()):
            raise AnalysisError(
                "IPv6 evaluations in a v4-only packing call; use "
                "pack_parsed2 (or the streaming driver, which handles "
                "both families)"
            )
        return out

    def pack_lines(self, lines: list[str], batch_size: int | None = None) -> np.ndarray:
        return self.pack_parsed([parse_line(ln) for ln in lines], batch_size)

    def pack_lines2(
        self, lines: list[str], batch_size: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        return self.pack_parsed2([parse_line(ln) for ln in lines], batch_size)


# ---------------------------------------------------------------------------
# Stacked (grouped) form: BASELINE.json config #4 "multi-firewall batched
# ruleset match (vmap over rulesets)".  The flat rule matrix scans EVERY
# firewall's rows for every line; grouping lines by their ACL and stacking
# each ACL's rows into one padded slab drops the per-line cost from
# O(total rows) to O(max slab rows), with the match kernel vmapped over
# the group axis.  Grouping lines host-side is the rebuilt analog of the
# reference's shuffle partitioning (SURVEY.md §3c).
# ---------------------------------------------------------------------------


def stacked_slab_rows(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> int:
    """Rmax of :func:`stack_rules` without building the slab tensor."""
    g = max(packed.n_acls, 1)
    real = packed.rules[packed.rules[:, R_ACL] != NO_ACL]
    counts = np.bincount(real[:, R_ACL].astype(np.int64), minlength=g) if real.size else np.zeros(g, np.int64)
    rmax = max(int(counts.max()) if counts.size else 0, 1)
    if rmax > rule_block:
        rmax = ((rmax + rule_block - 1) // rule_block) * rule_block
    return rmax


def stack_rules(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> np.ndarray:
    """[G, Rmax, RULE_COLS] uint32: each ACL's expanded rows, padded.

    Row order inside each slab preserves global config order, so the
    first-match == min-local-row-index invariant carries over.  Rmax is
    padded to ``rule_block`` granularity when any slab exceeds one block
    (the scan path of the match kernel requires it).
    """
    g = max(packed.n_acls, 1)
    real = packed.rules[packed.rules[:, R_ACL] != NO_ACL]
    rmax = stacked_slab_rows(packed, rule_block)
    out = np.zeros((g, rmax, RULE_COLS), dtype=np.uint32)
    out[:, :, R_ACL] = NO_ACL
    fill = np.zeros(g, dtype=np.int64)
    for row in real:
        gid = int(row[R_ACL])
        out[gid, fill[gid]] = row
        fill[gid] += 1
    return out


def stacked_slab_rows6(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> int:
    """R6max of :func:`stack_rules6` without building the slab tensor."""
    g = max(packed.n_acls, 1)
    real = packed.rules6[packed.rules6[:, R6_ACL] != NO_ACL]
    counts = (
        np.bincount(real[:, R6_ACL].astype(np.int64), minlength=g)
        if real.size
        else np.zeros(g, np.int64)
    )
    rmax = max(int(counts.max()) if counts.size else 0, 1)
    if rmax > rule_block:
        rmax = ((rmax + rule_block - 1) // rule_block) * rule_block
    return rmax


def stack_rules6(packed: PackedRuleset, rule_block: int = RULE_BLOCK) -> np.ndarray:
    """[G, R6max, RULE6_COLS] uint32: each ACL's v6 rows, padded.

    The v6 twin of :func:`stack_rules` (BASELINE config #4 "vmap over
    rulesets"): slab row order preserves global config order so
    first-match == min local row carries over; NO_ACL padding never
    matches.
    """
    g = max(packed.n_acls, 1)
    real = packed.rules6[packed.rules6[:, R6_ACL] != NO_ACL]
    rmax = stacked_slab_rows6(packed, rule_block)
    out = np.zeros((g, rmax, RULE6_COLS), dtype=np.uint32)
    out[:, :, R6_ACL] = NO_ACL
    fill = np.zeros(g, dtype=np.int64)
    for row in real:
        gid = int(row[R6_ACL])
        out[gid, fill[gid]] = row
        fill[gid] += 1
    return out


def group_tuples(batch: np.ndarray, n_groups: int, lane: int) -> np.ndarray:
    """One-shot grouping: [B, TUPLE_COLS] rows -> [G, TUPLE_COLS, lane].

    Valid rows are bucketed by their ACL gid; raises if any bucket
    overflows ``lane`` (streaming callers use :class:`GroupBuffer`, which
    carries overflow to the next grouped batch instead).
    """
    out = np.zeros((n_groups, TUPLE_COLS, lane), dtype=np.uint32)
    valid = batch[batch[:, T_VALID] != 0]  # weighted rows bucket too
    if not valid.size:
        return out
    gids = valid[:, T_ACL].astype(np.int64)
    if gids.max() >= n_groups or np.bincount(gids, minlength=n_groups).max() > lane:
        raise ValueError("bucket overflow: raise lane or use GroupBuffer")
    sv, starts, ends = _bucket_by_gid(valid, gids, n_groups)
    for gid in range(n_groups):
        n = ends[gid] - starts[gid]
        if n:
            out[gid, :, :n] = sv[starts[gid]:ends[gid]].T
    return out


def _bucket_by_gid(valid_rows: np.ndarray, gids: np.ndarray, n_groups: int):
    """Stable-sort rows by gid; return (sorted_rows, starts, ends).

    The STABLE sort is load-bearing: intra-group line order must survive
    bucketing so grouped and flat paths see the same per-group sequences.
    """
    order = np.argsort(gids, kind="stable")
    sg = gids[order]
    starts = np.searchsorted(sg, np.arange(n_groups))
    ends = np.searchsorted(sg, np.arange(n_groups), side="right")
    return valid_rows[order], starts, ends


class GroupBuffer:
    """Streaming per-ACL bucketing with overflow carry.

    Feed packed row-major batches; grouped batches ``[G, TUPLE_COLS,
    lane]`` are emitted whenever some bucket has a full lane (draining all
    buckets simultaneously, shorter ones padded with valid=0), so memory
    stays bounded under group skew.
    """

    def __init__(self, n_groups: int, lane: int):
        self.n_groups = n_groups
        self.lane = lane
        self._q: list[list[np.ndarray]] = [[] for _ in range(n_groups)]
        self._qlen = np.zeros(n_groups, dtype=np.int64)

    def add(self, batch: np.ndarray) -> list[np.ndarray]:
        """Add a [B, TUPLE_COLS] batch; return any full grouped batches.

        Rows whose valid column carries a weight > 1 (coalesced input)
        bucket exactly like plain rows — the weight rides along in the
        row and the grouped compactor (compact_grouped_w) preserves it.
        """
        valid = batch[batch[:, T_VALID] != 0]
        if valid.size:
            gids = valid[:, T_ACL].astype(np.int64)
            sv, starts, ends = _bucket_by_gid(valid, gids, self.n_groups)
            for gid in range(self.n_groups):
                if ends[gid] > starts[gid]:
                    rows = sv[starts[gid]:ends[gid]]
                    self._q[gid].append(rows)
                    self._qlen[gid] += rows.shape[0]
        out = []
        while self._qlen.max(initial=0) >= self.lane:
            out.append(self._emit())
        return out

    def flush(self) -> list[np.ndarray]:
        """Emit remaining buffered lines as (padded) grouped batches."""
        out = []
        while self._qlen.max(initial=0) > 0:
            out.append(self._emit())
        return out

    def _emit(self) -> np.ndarray:
        out = np.zeros((self.n_groups, TUPLE_COLS, self.lane), dtype=np.uint32)
        for gid in range(self.n_groups):
            take = min(self.lane, int(self._qlen[gid]))
            filled = 0
            while filled < take:
                head = self._q[gid][0]
                n = min(head.shape[0], take - filled)
                out[gid, :, filled:filled + n] = head[:n].T
                filled += n
                if n == head.shape[0]:
                    self._q[gid].pop(0)
                else:
                    self._q[gid][0] = head[n:]
            self._qlen[gid] -= take
        return out


# ---------------------------------------------------------------------------
# Serialization (the analog of the reference pickling parser output to disk
# for shipment to map tasks — SURVEY.md §4.1).  JSON + npz: inspectable and
# dependency-free.
# ---------------------------------------------------------------------------


def save_packed(packed: PackedRuleset, path_prefix: str) -> None:
    np.savez_compressed(
        path_prefix + ".npz",
        rules=packed.rules,
        rules6=packed.rules6,
        deny_key=packed.deny_key,
        n_rules=np.int64(packed.n_rules),
        n_acls=np.int64(packed.n_acls),
    )
    meta = {
        "key_meta": [dataclasses.asdict(m) for m in packed.key_meta],
        "acl_gid": [[fw, acl, gid] for (fw, acl), gid in packed.acl_gid.items()],
        "bindings": [[fw, iface, gid] for (fw, iface), gid in packed.bindings.items()],
        "bindings_out": [
            [fw, iface, gid] for (fw, iface), gid in packed.bindings_out.items()
        ],
        "parse_skips": [[fw, lineno, reason] for fw, lineno, reason in packed.parse_skips],
    }
    with open(path_prefix + ".json", "w", encoding="utf-8") as f:
        json.dump(meta, f)


#: (lo, hi, name) column pairs that every rule row must keep ordered.  The
#: device predicate is the branch-free wraparound check (x - lo) <= (hi - lo)
#: on uint32, which assumes lo <= hi: an inverted pair would silently match
#: almost every value instead of matching nothing (ADVICE r4, medium).
_RANGE_COLS = (
    (R_PLO, R_PHI, "proto"),
    (R_SLO, R_SHI, "src"),
    (R_SPLO, R_SPHI, "sport"),
    (R_DLO, R_DHI, "dst"),
    (R_DPLO, R_DPHI, "dport"),
)


def validate_rule_ranges(rules: np.ndarray) -> None:
    """Reject rule rows with inverted lo/hi ranges.

    The parser refuses inverted ranges at parse time (aclparse), but a
    packed artifact saved by an older build may still carry one; under the
    wraparound predicate it would inflate that rule's hit count and remove
    it from the unused/deletion-candidate set with no error.  Fail loudly
    instead, naming the first offending row.
    """
    for lo, hi, name in _RANGE_COLS:
        bad = np.nonzero(rules[:, lo] > rules[:, hi])[0]
        if bad.size:
            row = int(bad[0])
            raise AnalysisError(
                f"packed ruleset row {row} has inverted {name} range "
                f"[{int(rules[row, lo])}, {int(rules[row, hi])}]"
                f" ({bad.size} offending row(s) total); the artifact was "
                "likely written by a pre-wraparound-check build — re-pack "
                "it with parse-acls/convert"
            )


def validate_rule6_ranges(rules6: np.ndarray) -> None:
    """Reject v6 rule rows with inverted lo/hi ranges (v4 twin above).

    Scalar columns use the same check; 128-bit address bounds compare
    lexicographically over their big-endian limbs.
    """
    if rules6.shape[0] == 0:
        return
    for lo, hi, name in ((R6_PLO, R6_PHI, "proto"), (R6_SPLO, R6_SPHI, "sport"),
                         (R6_DPLO, R6_DPHI, "dport")):
        bad = np.nonzero(rules6[:, lo] > rules6[:, hi])[0]
        if bad.size:
            raise AnalysisError(
                f"packed v6 ruleset row {int(bad[0])} has inverted {name} "
                f"range ({bad.size} offending row(s) total); re-pack the "
                "artifact with parse-acls/convert"
            )
    for lo0, hi0, name in ((R6_SLO, R6_SHI, "src"), (R6_DLO, R6_DHI, "dst")):
        lo_limbs = rules6[:, lo0:lo0 + 4].astype(np.uint64)
        hi_limbs = rules6[:, hi0:hi0 + 4].astype(np.uint64)
        n = rules6.shape[0]
        lt = np.zeros(n, dtype=bool)
        gt = np.zeros(n, dtype=bool)
        for i in range(4):  # big-endian lexicographic compare
            lt |= ~gt & (lo_limbs[:, i] < hi_limbs[:, i])
            gt |= ~lt & (lo_limbs[:, i] > hi_limbs[:, i])
        bad = np.nonzero(gt)[0]
        if bad.size:
            raise AnalysisError(
                f"packed v6 ruleset row {int(bad[0])} has inverted {name} "
                f"address range ({bad.size} offending row(s) total); re-pack "
                "the artifact with parse-acls/convert"
            )


def load_packed(path_prefix: str) -> PackedRuleset:
    z = np.load(path_prefix + ".npz")
    with open(path_prefix + ".json", "r", encoding="utf-8") as f:
        meta = json.load(f)
    validate_rule_ranges(z["rules"])
    # rules6 absent in pre-v6 artifacts: those are pure-v4 by construction
    rules6 = z["rules6"] if "rules6" in z.files else None
    if rules6 is not None:
        validate_rule6_ranges(rules6)
    return PackedRuleset(
        rules=z["rules"],
        rules6=rules6,
        n_rules=int(z["n_rules"]),
        n_acls=int(z["n_acls"]),
        key_meta=[KeyMeta(**m) for m in meta["key_meta"]],
        acl_gid={(fw, acl): gid for fw, acl, gid in meta["acl_gid"]},
        deny_key=z["deny_key"],
        bindings={(fw, iface): gid for fw, iface, gid in meta["bindings"]},
        bindings_out={
            (fw, iface): gid for fw, iface, gid in meta.get("bindings_out", [])
        },
        parse_skips=[
            (fw, int(lineno), reason)
            for fw, lineno, reason in meta.get("parse_skips", [])
        ],
    )
