"""Synthetic ASA configs and syslog — test fixtures and benchmark feedstock.

SURVEY.md §5 calls for "a synthetic-syslog generator (parameterized by
ruleset so that expected hits are known by construction)".  Generation
intent here is only a *bias* — ground truth for every test comes from the
oracle, never from the generator — so overlapping rules shadowing each
other can't make expectations silently wrong.

Two tiers:

- text tier: ASA config text + raw syslog lines (exercises the full parse
  path end-to-end);
- packed tier: vectorized numpy generation of tuple batches straight
  against a PackedRuleset (feeds device benchmarks at rates the text
  renderer can't reach).
"""

from __future__ import annotations

import numpy as np

from .aclparse import int_to_ip6, u32_to_ip
from .pack import (
    PackedRuleset,
    R_ACL,
    R_DHI,
    R_DLO,
    R_DPHI,
    R_DPLO,
    R_PHI,
    R_PLO,
    R_SHI,
    R_SLO,
    R_SPHI,
    R_SPLO,
    R6_ACL,
    R6_DHI,
    R6_DLO,
    R6_DPHI,
    R6_DPLO,
    R6_PHI,
    R6_PLO,
    R6_SHI,
    R6_SLO,
    R6_SPHI,
    R6_SPLO,
    T_VALID,
    T6_DPORT,
    T6_DST,
    T6_PROTO,
    T6_SPORT,
    T6_SRC,
    T6_VALID,
    TUPLE_COLS,
    TUPLE6_COLS,
    NO_ACL,
    limbs_u128,
    u128_limbs,
)

_COMMON_PROTOS = np.array([6, 6, 6, 17, 17, 1], dtype=np.uint32)


def synth_config(
    n_acls: int = 4,
    rules_per_acl: int = 32,
    n_groups: int = 4,
    seed: int = 0,
    hostname: str = "fw1",
    egress_acls: bool = False,
    v6_fraction: float = 0.0,
) -> str:
    """Generate ASA configuration text with object-groups and varied ACEs.

    ``v6_fraction`` > 0 spells that share of ACEs with IPv6 operands
    (any6 / host literals / prefixes) — the unified-ACL tier; 0 (the
    default) keeps every historical fixture bit-identical.
    """
    rng = np.random.default_rng(seed)
    lines = [f"hostname {hostname}", "!"]

    group_names = []
    for g in range(n_groups):
        name = f"NETGRP{g}"
        group_names.append(name)
        lines.append(f"object-group network {name}")
        for _ in range(int(rng.integers(2, 5))):
            if rng.random() < 0.5:
                lines.append(f" network-object host 10.{g}.{rng.integers(0,255)}.{rng.integers(1,255)}")
            else:
                lines.append(f" network-object 172.{16+g}.{rng.integers(0,255)}.0 255.255.255.0")
    lines.append("object-group service WEBPORTS tcp")
    lines.append(" port-object eq 80")
    lines.append(" port-object eq 443")
    lines.append(" port-object range 8000 8100")

    protos = ["tcp", "udp", "ip", "icmp"]
    for a in range(n_acls):
        acl = f"ACL{a}"
        for r in range(rules_per_acl):
            action = "permit" if rng.random() < 0.7 else "deny"
            proto = protos[int(rng.integers(0, len(protos)))]
            if v6_fraction and rng.random() < v6_fraction:
                # v6 ACE: any6 / host literal / prefix operands
                roll = rng.random()
                if roll < 0.3:
                    src = "any6"
                elif roll < 0.65:
                    src = f"host 2001:db8:{a:x}::{rng.integers(1, 0xFFFF):x}"
                else:
                    src = f"2001:db8:{rng.integers(0, 16):x}::/{int(rng.choice([48, 64, 96]))}"
                if rng.random() < 0.4:
                    dst = "any6"
                else:
                    dst = f"2001:db8:{rng.integers(0, 16):x}:1::/{int(rng.choice([64, 80]))}"
                if proto == "icmp":
                    proto = "icmp6"
                port = ""
                if proto in ("tcp", "udp") and rng.random() < 0.4:
                    port = f" eq {rng.integers(1, 1024)}"
                lines.append(
                    f"access-list {acl} extended {action} {proto} {src} {dst}{port}"
                )
                continue
            # source
            roll = rng.random()
            if roll < 0.25:
                src = "any"
            elif roll < 0.5:
                src = f"object-group {group_names[int(rng.integers(0, n_groups))]}"
            elif roll < 0.75:
                src = f"host 192.168.{a}.{rng.integers(1, 255)}"
            else:
                src = f"10.{rng.integers(0, 32)}.0.0 255.255.0.0"
            # destination
            if rng.random() < 0.4:
                dst = "any"
            else:
                dst = f"198.51.{rng.integers(0, 100)}.0 255.255.255.0"
            # destination port spec
            port = ""
            if proto in ("tcp", "udp"):
                roll = rng.random()
                if roll < 0.3:
                    port = f" eq {rng.integers(1, 1024)}"
                elif roll < 0.5:
                    lo = int(rng.integers(1024, 30000))
                    port = f" range {lo} {lo + int(rng.integers(1, 5000))}"
                elif proto == "tcp" and roll < 0.6:
                    port = " object-group WEBPORTS"
            lines.append(f"access-list {acl} extended {action} {proto} {src} {dst}{port}")
        lines.append(f"access-group ACL{a} in interface if{a}")
        if egress_acls:
            # the same ACL also filters traffic EXITING interface eg{a}:
            # connection lines whose egress side is eg{a} get a second
            # evaluation against it (SURVEY.md §4.3 mapper semantics)
            lines.append(f"access-group ACL{a} out interface eg{a}")
    return "\n".join(lines) + "\n"


def synth_tuples(
    packed: PackedRuleset,
    n: int,
    seed: int = 0,
    miss_fraction: float = 0.1,
) -> np.ndarray:
    """Vectorized batch of packed tuples biased to hit real rules.

    A ``miss_fraction`` of lines draw fully random field values (mostly
    landing in implicit deny), the rest sample inside a random expanded
    ACE's ranges.
    """
    rng = np.random.default_rng(seed)
    rules = packed.rules.astype(np.int64)
    real = rules[:, R_ACL] != int(NO_ACL)
    rules = rules[real]
    if rules.shape[0] == 0:
        raise ValueError("packed ruleset has no rules")
    pick = rng.integers(0, rules.shape[0], size=n)
    rr = rules[pick]

    def _within(lo_col: int, hi_col: int) -> np.ndarray:
        lo, hi = rr[:, lo_col], rr[:, hi_col]
        return rng.integers(lo, hi + 1)

    proto = _within(R_PLO, R_PHI)
    full_proto = (rr[:, R_PLO] == 0) & (rr[:, R_PHI] == 255)
    proto = np.where(full_proto, rng.choice(_COMMON_PROTOS, size=n).astype(np.int64), proto)

    out = np.zeros((n, TUPLE_COLS), dtype=np.uint32)
    out[:, 0] = rr[:, R_ACL].astype(np.uint32)
    out[:, 1] = proto.astype(np.uint32)
    out[:, 2] = _within(R_SLO, R_SHI).astype(np.uint32)
    out[:, 3] = _within(R_SPLO, R_SPHI).astype(np.uint32)
    out[:, 4] = _within(R_DLO, R_DHI).astype(np.uint32)
    out[:, 5] = _within(R_DPLO, R_DPHI).astype(np.uint32)
    out[:, T_VALID] = 1

    miss = rng.random(n) < miss_fraction
    n_miss = int(miss.sum())
    if n_miss:
        out[miss, 1] = rng.integers(0, 256, size=n_miss)
        out[miss, 2] = rng.integers(0, 1 << 32, size=n_miss, dtype=np.uint32)
        out[miss, 3] = rng.integers(0, 1 << 16, size=n_miss)
        out[miss, 4] = rng.integers(0, 1 << 32, size=n_miss, dtype=np.uint32)
        out[miss, 5] = rng.integers(0, 1 << 16, size=n_miss)
    return out


# ---------------------------------------------------------------------------
# Flow-repetition tier (ISSUE 5): real firewall traffic logs the same
# 5-tuple over and over with Zipf-like skew (the heavy-hitter setting of
# Metwally et al.'s Space-Saving work), which is exactly when the
# coalescing ingest tier pays off.  This generator dials that skew so
# benches and tests can target a compaction ratio by construction.
# ---------------------------------------------------------------------------


def flow_pool(
    packed: PackedRuleset,
    n_flows: int,
    seed: int = 0,
    miss_fraction: float = 0.1,
) -> np.ndarray:
    """A pool of DISTINCT candidate flows: ``[m, TUPLE_COLS]``, m <= n_flows.

    Drawn via :func:`synth_tuples` then deduplicated in generation order
    (random draws can collide), so :func:`expected_unique` over the
    returned pool size is exact.
    """
    t = synth_tuples(packed, n_flows, seed=seed, miss_fraction=miss_fraction)
    view = np.ascontiguousarray(t).view(
        [("", np.uint32)] * t.shape[1]
    ).ravel()
    _, first = np.unique(view, return_index=True)
    first.sort()
    return t[first]


def zipf_weights(m: int, skew: float) -> np.ndarray:
    """Normalized Zipf(s) pmf over ranks 1..m (``skew=0`` -> uniform)."""
    if m < 1:
        raise ValueError("need at least one flow")
    p = 1.0 / np.arange(1, m + 1, dtype=np.float64) ** float(skew)
    return p / p.sum()


def expected_unique(n: int, m: int, skew: float) -> float:
    """E[distinct flows] among ``n`` draws from the Zipf(s) pool of ``m``.

    Independent draws: E[U] = sum_k (1 - (1 - p_k)^n).  The property
    test pins generated corpora to this within ±10%, so a bench asking
    for compaction ratio r = n / E[U] actually gets it.
    """
    p = zipf_weights(m, skew)
    return float((1.0 - (1.0 - p) ** n).sum())


def synth_flow_tuples(
    packed: PackedRuleset,
    n: int,
    n_flows: int,
    skew: float = 1.0,
    seed: int = 0,
    miss_fraction: float = 0.1,
) -> np.ndarray:
    """``n`` tuple rows drawn with Zipf(s) repetition from a flow pool.

    Flow rank k repeats with probability ∝ 1/k**skew; ``skew=0`` gives
    uniform draws (compaction ratio -> n/m for n >> m), larger skew
    concentrates traffic on the head flows.  Deterministic in ``seed``
    (pool and draws both).  The per-batch compaction ratio a stream run
    sees is ~batch_size / expected_unique(batch_size, pool, skew).
    """
    pool = flow_pool(packed, n_flows, seed=seed, miss_fraction=miss_fraction)
    rng = np.random.default_rng(seed ^ 0x5EEDF10)
    idx = rng.choice(pool.shape[0], size=n, p=zipf_weights(pool.shape[0], skew))
    return pool[idx]


def synth_tuples6(
    packed: PackedRuleset,
    n: int,
    seed: int = 0,
    miss_fraction: float = 0.1,
) -> np.ndarray:
    """v6 twin of :func:`synth_tuples`: [n, TUPLE6_COLS] biased at rules6.

    128-bit address sampling runs per-row with Python ints (arbitrary-
    precision ranges); v6 feedstock volumes are test/bench-mix scale, not
    the 1e8-line packed v4 tier, so this stays simple and exact.
    """
    import random as _random

    rng = np.random.default_rng(seed)
    prng = _random.Random(seed ^ 0x76C0FFEE)
    r6 = packed.rules6
    real = r6[r6[:, R6_ACL] != NO_ACL]
    if real.shape[0] == 0:
        raise ValueError("packed ruleset has no v6 rules")
    pick = rng.integers(0, real.shape[0], size=n)
    miss = rng.random(n) < miss_fraction
    out = np.zeros((n, TUPLE6_COLS), dtype=np.uint32)
    for i in range(n):
        row = real[pick[i]]
        if miss[i]:
            out[i, T6_PROTO] = prng.randrange(256)
            out[i, T6_SRC:T6_SRC + 4] = u128_limbs(prng.getrandbits(128))
            out[i, T6_SPORT] = prng.randrange(1 << 16)
            out[i, T6_DST:T6_DST + 4] = u128_limbs(prng.getrandbits(128))
            out[i, T6_DPORT] = prng.randrange(1 << 16)
            out[i, 0] = row[R6_ACL]
            out[i, T6_VALID] = 1
            continue
        slo = limbs_u128(*row[R6_SLO:R6_SLO + 4])
        shi = limbs_u128(*row[R6_SHI:R6_SHI + 4])
        dlo = limbs_u128(*row[R6_DLO:R6_DLO + 4])
        dhi = limbs_u128(*row[R6_DHI:R6_DHI + 4])
        proto = prng.randint(int(row[R6_PLO]), int(row[R6_PHI]))
        if row[R6_PLO] == 0 and row[R6_PHI] == 255:
            proto = int(_COMMON_PROTOS[prng.randrange(len(_COMMON_PROTOS))])
        out[i, 0] = row[R6_ACL]
        out[i, T6_PROTO] = proto
        out[i, T6_SRC:T6_SRC + 4] = u128_limbs(prng.randint(slo, shi))
        out[i, T6_SPORT] = prng.randint(int(row[R6_SPLO]), int(row[R6_SPHI]))
        out[i, T6_DST:T6_DST + 4] = u128_limbs(prng.randint(dlo, dhi))
        out[i, T6_DPORT] = prng.randint(int(row[R6_DPLO]), int(row[R6_DPHI]))
        out[i, T6_VALID] = 1
    return out


def render_syslog6(
    packed: PackedRuleset,
    tuples6: np.ndarray,
    seed: int = 0,
    timestamp: str = "Jul 29 07:48:01",
    variety: float = 0.0,
) -> list[str]:
    """Render v6 tuple batches as ASA syslog text (text tier).

    Mirrors :func:`render_syslog`: 106100 by default; with ``variety`` a
    fraction of eligible lines render as the other handled message
    classes (106023, 302013/302015, 106001, 106006, 106015) with v6
    literals, constrained by protocol and resolvable bindings.
    """
    gid_to_name = {gid: (fw, acl) for (fw, acl), gid in packed.acl_gid.items()}
    in_iface = {}
    for (fw, iface), gid in packed.bindings.items():
        in_iface.setdefault((fw, gid), iface)
    out_ifaces: dict[str, list[str]] = {}
    for (fw, iface), _gid in packed.bindings_out.items():
        out_ifaces.setdefault(fw, []).append(iface)
    rng = np.random.default_rng(seed)
    verdicts = rng.random(tuples6.shape[0])
    kinds = rng.random(tuples6.shape[0])
    picks = rng.integers(0, 1 << 30, size=tuples6.shape[0])
    out = []
    for i, row in enumerate(tuples6):
        if not row[T6_VALID]:
            out.append(f"{timestamp} noise : not an ASA message")
            continue
        gid = int(row[0])
        fw, acl = gid_to_name[gid]
        proto = int(row[T6_PROTO])
        pname = _PROTO_NAMES.get(proto, str(proto))
        src = int_to_ip6(limbs_u128(*row[T6_SRC:T6_SRC + 4]))
        dst = int_to_ip6(limbs_u128(*row[T6_DST:T6_DST + 4]))
        sport, dport = int(row[T6_SPORT]), int(row[T6_DPORT])
        iface = in_iface.get((fw, gid))

        if variety and kinds[i] < variety:
            out.append(_variety_line(
                timestamp, fw, acl, pname, proto, src, dst, sport, dport,
                iface, out_ifaces, int(picks[i]), icmp_protos=(1, 58),
            ))
            continue

        verdict = "permitted" if verdicts[i] < 0.8 else "denied"
        if proto in (1, 58):
            paren_s, paren_d = dport, 0  # icmp type rides dport
        else:
            paren_s, paren_d = sport, dport
        out.append(
            f"{timestamp} {fw} : %ASA-6-106100: access-list {acl} {verdict} {pname} "
            f"inside/{src}({paren_s}) -> outside/{dst}({paren_d}) hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out


def synth_syslog_file(
    packed: PackedRuleset,
    path: str,
    n_lines: int,
    seed: int = 0,
    miss_fraction: float = 0.1,
    chunk: int = 1 << 18,
) -> None:
    """Write ``n_lines`` of synthetic ASA syslog text to ``path``.

    Chunked generation keeps memory bounded; the text round-trips the real
    parse path (text tier), so this is the feedstock for end-to-end
    benchmarks and tests.
    """
    with open(path, "w", encoding="utf-8") as f:
        remaining = n_lines
        i = 0
        while remaining > 0:
            m = min(chunk, remaining)
            t = synth_tuples(packed, m, seed=seed + i, miss_fraction=miss_fraction)
            f.write("\n".join(render_syslog(packed, t, seed=seed + i)))
            f.write("\n")
            remaining -= m
            i += 1


_PROTO_NAMES = {6: "tcp", 17: "udp", 1: "icmp", 58: "icmp6"}



def _variety_line(
    timestamp: str, fw: str, acl: str, pname: str, proto: int,
    src: str, dst: str, sport: int, dport: int,
    iface, out_ifaces: dict, pick: int, icmp_protos: tuple,
) -> str:
    """One non-106100 message line (shared by both family renderers).

    Eligibility mirrors what the parsers can resolve: 106023 always
    (names the ACL); the connection/deny classes need a resolvable
    ingress interface and a TCP/UDP protocol.  ``icmp_protos`` is the
    family's ICMP set ((1,) for v4, (1, 58) for v6) for the 106023
    type/code rendering.
    """
    eligible = ["106023"]
    if iface is not None and proto in (6, 17):
        eligible.append("302013")
        eligible.append("106001" if proto == 6 else "106006")
        if proto == 6:
            eligible.append("106015")
    kind = eligible[pick % len(eligible)]
    if kind == "106023":
        if proto in icmp_protos:
            ep = f"src inside:{src} dst outside:{dst} (type {dport}, code 0)"
        else:
            ep = f"src inside:{src}/{sport} dst outside:{dst}/{dport}"
        return (
            f'{timestamp} {fw} : %ASA-4-106023: Deny {pname} {ep} '
            f'by access-group "{acl}" [0x0, 0x0]'
        )
    if kind == "302013":
        egs = out_ifaces.get(fw)
        egress = egs[pick % len(egs)] if egs else "outside"
        tname = "TCP" if proto == 6 else "UDP"
        mid = "302013" if proto == 6 else "302015"
        return (
            f"{timestamp} {fw} : %ASA-6-{mid}: Built inbound {tname} "
            f"connection {pick} for {iface}:{src}/{sport} "
            f"({src}/{sport}) to {egress}:{dst}/{dport} ({dst}/{dport})"
        )
    if kind == "106001":
        return (
            f"{timestamp} {fw} : %ASA-2-106001: Inbound TCP connection "
            f"denied from {src}/{sport} to {dst}/{dport} flags SYN "
            f"on interface {iface}"
        )
    if kind == "106015":
        return (
            f"{timestamp} {fw} : %ASA-6-106015: Deny TCP (no connection) "
            f"from {src}/{sport} to {dst}/{dport} flags RST "
            f"on interface {iface}"
        )
    return (
        f"{timestamp} {fw} : %ASA-2-106006: Deny inbound UDP "
        f"from {src}/{sport} to {dst}/{dport} on interface {iface}"
    )


def render_syslog(
    packed: PackedRuleset,
    tuples: np.ndarray,
    seed: int = 0,
    timestamp: str = "Jul 29 07:48:01",
    variety: float = 0.0,
) -> list[str]:
    """Render packed tuples back into raw ASA syslog text.

    By default every valid tuple renders as a 106100 line (names the ACL
    directly — no binding inverse needed).  With ``variety`` > 0, that
    fraction of eligible lines render as other handled message classes
    (106023, 302013, 106001, 106006, 106015), constrained by protocol and
    by which interfaces the packed bindings make resolvable.  A 302013
    rendered with an out-bound egress interface yields TWO evaluations
    downstream — the oracle remains ground truth for every statistic.
    """
    gid_to_name = {gid: (fw, acl) for (fw, acl), gid in packed.acl_gid.items()}
    # binding inverses: (fw, gid) -> an ingress iface; fw -> egress ifaces
    in_iface = {}
    for (fw, iface), gid in packed.bindings.items():
        in_iface.setdefault((fw, gid), iface)
    out_ifaces: dict[str, list[str]] = {}
    for (fw, iface), _gid in packed.bindings_out.items():
        out_ifaces.setdefault(fw, []).append(iface)
    rng = np.random.default_rng(seed)
    verdicts = rng.random(tuples.shape[0])
    kinds = rng.random(tuples.shape[0])
    picks = rng.integers(0, 1 << 30, size=tuples.shape[0])
    out = []
    for i, row in enumerate(tuples):
        if not row[T_VALID]:
            out.append(f"{timestamp} noise : not an ASA message")
            continue
        gid = int(row[0])
        fw, acl = gid_to_name[gid]
        proto = int(row[1])
        pname = _PROTO_NAMES.get(proto, str(proto))
        src, dst = u32_to_ip(int(row[2])), u32_to_ip(int(row[4]))
        sport, dport = int(row[3]), int(row[5])
        iface = in_iface.get((fw, gid))

        if variety and kinds[i] < variety:
            out.append(_variety_line(
                timestamp, fw, acl, pname, proto, src, dst, sport, dport,
                iface, out_ifaces, int(picks[i]), icmp_protos=(1,),
            ))
            continue

        verdict = "permitted" if verdicts[i] < 0.8 else "denied"
        if proto == 1:
            # icmp: type travels in the dport column; render as (type)(code 0)
            paren_s, paren_d = dport, 0
        else:
            paren_s, paren_d = sport, dport
        out.append(
            f"{timestamp} {fw} : %ASA-6-106100: access-list {acl} {verdict} {pname} "
            f"inside/{src}({paren_s}) -> outside/{dst}({paren_d}) hit-cnt 1 first hit [0x0, 0x0]"
        )
    return out
