"""Central configuration (the reference's ``config.py`` analog, SURVEY.md L0).

The reference keeps module-level constants naming the firewall inventory and
job paths; scripts ``import config`` and read them.  We keep that shape for
compatibility (module constants below) and add a typed, immutable
:class:`AnalysisConfig` used by the CLI and runtime, since the TPU path has
real tunables (batch size, sketch geometry, mesh shape) that the Hadoop path
never needed.
"""

from __future__ import annotations

import dataclasses
import os

# ---------------------------------------------------------------------------
# Reference-style module constants (SURVEY.md §3: "module-level constants:
# firewall list, credentials/paths, HDFS/job paths").  Paths are local rather
# than HDFS; the firewall inventory maps a firewall name to the path of its
# saved configuration.
# ---------------------------------------------------------------------------

#: Firewall inventory: name -> path of the saved ASA configuration file.
FIREWALLS: dict[str, str] = {}

#: Directory where `parse-acls` (the getaccesslists analog) writes parsed,
#: serialized rulesets.
RULESET_DIR = os.environ.get("RA_RULESET_DIR", "rulesets")

#: Directory for analysis outputs (reports, checkpoints).
OUTPUT_DIR = os.environ.get("RA_OUTPUT_DIR", "out")


# ---------------------------------------------------------------------------
# Typed runtime configuration.
# ---------------------------------------------------------------------------


#: Maximum CMS depth — ops/hashing.py guarantees this many independent
#: multiply-shift constants (asserted there against MS_CONSTANTS).
MAX_CMS_DEPTH = 8


# ---------------------------------------------------------------------------
# Weighted-input compatibility — ONE declarative table (DESIGN §11/§18).
#
# A weighted batch (coalesced on the fly, or a RAWIREv3 wire file whose
# rows carry original-line weights) is only correct through device
# formulations that are weight-linear (adds scale with the weight plane)
# or idempotent (max gates on weight>0).  Three consumers read this
# table so the refusal set can never drift between them:
#
# - AnalysisConfig.__post_init__ — config-time refusal of `coalesce`
#   with an incompatible impl choice;
# - runtime/stream.py::_check_weighted_input_config — run-time refusal
#   when a weighted WIRE input reaches a driver whose config the
#   validator accepted (it never saw the input's weights);
# - ruleset_analysis_tpu/verify — the static linter DERIVES each impl
#   combination's weight-linearity verdict from its traced jaxpr and
#   cross-checks the derived refusal set against exactly this table
#   (tests/test_ralint.py pins the equality).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WeightedRefusal:
    """One impl choice that cannot accept weighted (coalesced) inputs."""

    #: AnalysisConfig field and value naming the incompatible choice.
    field: str
    value: str
    #: Human reason, embedded in both refusal messages.
    reason: str
    #: The linearity verdict the static linter must derive for programs
    #: built with this choice ("unprovable" = opaque kernel the taint
    #: walk cannot enter; "float-bounded" = linear but through an f32
    #: formulation whose exactness is range-bounded).
    lint_verdict: str
    #: Config-time `coalesce` refusal bound: None = refuse always;
    #: an int N = refuse only when batch_size >= N (below it the
    #: formulation's own guards keep the combination exact).
    coalesce_min_batch: int | None = None


WEIGHTED_INPUT_REFUSALS: tuple[WeightedRefusal, ...] = (
    WeightedRefusal(
        field="match_impl",
        value="pallas_fused",
        reason=(
            "the experimental pallas_fused kernel's in-VMEM count "
            "histogram is not weight-linear (it adds ONE per valid "
            "line, so a weight-w row would silently count as one "
            "line); use the default match_impl"
        ),
        lint_verdict="unprovable",
    ),
    WeightedRefusal(
        field="counts_impl",
        value="matmul",
        reason=(
            "the matmul counts formulation is exact only while per-key "
            "per-chunk sums stay < 2^24 (f32 integer range), and a "
            "weighted chunk's summed weights are bounded by the "
            "ORIGINAL corpus lines behind it, not the stored batch "
            "size its shape guard sees; use 'scatter' or 'reduce'"
        ),
        lint_verdict="float-bounded",
        coalesce_min_batch=1 << 24,
    ),
)

#: Per-chunk summed-weight ceiling for weighted wire inputs: the exact-
#: counts accumulator's carry detection (ops/counts.py add64) assumes
#: per-chunk deltas < 2^32.  A plain chunk satisfies it by shape; a
#: weighted chunk's delta is the original line count behind its rows, so
#: the stream drivers refuse chunks at or past this bound
#: (runtime/stream.py::_WireFileSource._check_chunk_weight) — the
#: run-time member of the weighted-input refusal set, which no static
#: check can prove away (it depends on the data, not the program).
WEIGHTED_CHUNK_WEIGHT_LIMIT = 1 << 32


@dataclasses.dataclass(frozen=True)
class SketchConfig:
    """Geometry of the mergeable sketches kept on device.

    Defaults follow the usual error bounds: a count-min sketch of width ``w``
    and depth ``d`` over-estimates by at most ``e*N/w`` with probability
    ``1 - exp(-d)``; a HyperLogLog with ``m = 2**hll_p`` registers has
    relative error ``~1.04/sqrt(m)``.

    Validation lives here (not in the CLI) so every entry point — CLI,
    library callers, tests — gets the same clean errors.
    """

    cms_width: int = 1 << 14
    cms_depth: int = 4
    hll_p: int = 8  # 256 registers/rule -> ~6.5% per-rule cardinality error
    topk_capacity: int = 256  # host-side talker-summary size per ACL
    topk_chunk_candidates: int = 64  # device top_k candidates fed per chunk
    #: Depth of the (acl, src) talker CMS.  Unlike the per-rule CMS, its
    #: estimates only rank talkers (the tracker keeps the max estimate
    #: across chunks), so a shallow sketch suffices — and its scatter cost
    #: scales with depth x batch, a large share of the whole device step.
    talk_cms_depth: int = 2
    #: Candidate-SELECTION subsampling: pick per-chunk talker candidates
    #: from every 2**shift-th line instead of the whole batch.  The talker
    #: CMS still absorbs EVERY line (estimates are exact-as-before); only
    #: the two candidate-table scatters shrink — the TPU trace shows the
    #: step is scatter-bound, and heavy hitters by definition recur, so a
    #: stride sample still surfaces them (a chunk where one is missed
    #: feeds it next chunk).  0 = select from the full batch (bit-exact
    #: pre-round-4 candidates).
    topk_sample_shift: int = 0
    #: Deferred candidate SELECTION cadence: run the candidate table +
    #: top_k on every Nth chunk only (Space-Saving spirit — heavy
    #: hitters recur across chunks, so a chunk-stride sample still
    #: surfaces them).  The talker CMS absorbs EVERY line regardless, so
    #: reported estimates are untouched; skipped chunks feed est=0
    #: candidates the host tracker ignores.  Deterministic in the chunk
    #: salt: resume replays the same selection schedule.  1 = select
    #: every chunk (the historical behavior, byte-identical HLO).
    topk_every: int = 1

    def __post_init__(self) -> None:
        if self.cms_width < 2 or self.cms_width & (self.cms_width - 1):
            raise ValueError(f"cms_width must be a power of two >= 2, got {self.cms_width}")
        if not 1 <= self.cms_depth <= MAX_CMS_DEPTH:
            raise ValueError(f"cms_depth must be in 1..{MAX_CMS_DEPTH}, got {self.cms_depth}")
        if not 1 <= self.talk_cms_depth <= MAX_CMS_DEPTH:
            raise ValueError(
                f"talk_cms_depth must be in 1..{MAX_CMS_DEPTH}, got {self.talk_cms_depth}"
            )
        if not 1 <= self.hll_p <= 16:
            raise ValueError(f"hll_p must be in 1..16, got {self.hll_p}")
        if self.topk_capacity < 1 or self.topk_chunk_candidates < 1:
            raise ValueError("topk_capacity and topk_chunk_candidates must be >= 1")
        if not 0 <= self.topk_sample_shift <= 8:
            raise ValueError(
                f"topk_sample_shift must be in 0..8, got {self.topk_sample_shift}"
            )
        if not 1 <= self.topk_every <= 4096:
            raise ValueError(
                f"topk_every must be in 1..4096, got {self.topk_every}"
            )

    @property
    def hll_m(self) -> int:
        return 1 << self.hll_p


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Policy knobs of the metrics-driven elastic autoscaler
    (runtime/autoscale.py).

    The policy reads two canonical signals sampled from the live metrics
    plane — **pressure** (fraction of recent wall time the pipeline was
    producer-backpressured / queue-saturated: the device tier cannot keep
    up, scale OUT) and **starvation** (fraction of recent wall time the
    device tier sat idle waiting for input: capacity is excess, scale
    IN) — and turns them into planned scale events only when a signal
    holds over a full ``sustain_sec`` window.  Flap damping is threefold:
    the sustain window itself, a ``cooldown_sec`` dead time after every
    decision, and the hysteresis gap between the two thresholds (both
    signals cannot be sustained simultaneously).  ``reform_budget``
    bounds the scale re-formations of one run the way ``--max-reforms``
    bounds failure re-formations; 0 = observe-only (decisions are
    logged with evidence but never actuated).
    """

    min_world: int = 1
    max_world: int = 0  # 0 = everything provisioned (devices / launcher pool)
    initial_world: int = 0  # 0 = the smallest allowed world
    out_threshold: float = 0.5  # sustained pressure >= this => scale out
    in_threshold: float = 0.8  # sustained starvation >= this => scale in
    sustain_sec: float = 3.0  # a signal must hold this long to count
    cooldown_sec: float = 10.0  # dead time after every decision
    reform_budget: int = 4  # scale re-formations allowed (0 = observe-only)
    poll_sec: float = 0.5  # metrics sampling cadence
    #: scripted decision schedule for drills/tests ("out@T,in@T": fire
    #: each entry T seconds after the policy engine starts observing,
    #: in order); empty = decide from the live signals
    plan: str = ""

    def __post_init__(self) -> None:
        if self.min_world < 1:
            raise ValueError(f"min_world must be >= 1, got {self.min_world}")
        if self.max_world < 0 or (
            self.max_world and self.max_world < self.min_world
        ):
            raise ValueError(
                f"max_world must be 0 (= provisioned) or >= min_world, got "
                f"{self.max_world} (min_world {self.min_world})"
            )
        if self.initial_world < 0 or (
            self.initial_world
            and not (
                self.min_world
                <= self.initial_world
                <= (self.max_world or self.initial_world)
            )
        ):
            raise ValueError(
                f"initial_world must be 0 or within "
                f"[{self.min_world}, {self.max_world or 'max'}], got "
                f"{self.initial_world}"
            )
        for name in ("out_threshold", "in_threshold"):
            v = getattr(self, name)
            if not 0.0 < v <= 1.0:
                raise ValueError(f"{name} must be in (0, 1], got {v}")
        if self.sustain_sec <= 0 or self.poll_sec <= 0:
            raise ValueError("sustain_sec and poll_sec must be > 0")
        if self.cooldown_sec < 0:
            raise ValueError("cooldown_sec must be >= 0")
        if self.reform_budget < 0:
            raise ValueError("reform_budget must be >= 0")
        # validate the scripted plan eagerly (bad specs fail at config
        # time like every other knob), without importing the engine
        for part in filter(None, (p.strip() for p in self.plan.split(","))):
            d, _, t = part.partition("@")
            if d not in ("out", "in"):
                raise ValueError(
                    f"autoscale plan entry {part!r}: direction must be "
                    "'out' or 'in'"
                )
            try:
                if float(t) < 0:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"autoscale plan entry {part!r}: want DIRECTION@SECONDS"
                ) from None

    def to_dict(self) -> dict:
        """JSON-serializable image (elastic supervisor -> worker handoff)."""
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "AutoscaleConfig":
        return AutoscaleConfig(**d)


@dataclasses.dataclass(frozen=True)
class DevprofConfig:
    """Device attribution capture window (runtime/devprof.py, DESIGN §14).

    ``run/serve --devprof-out DIR`` arms one bounded ``jax.profiler``
    window: dispatches ``1..warmup`` run unprofiled (compile + cache
    warm), the next ``steps`` dispatches are captured, parsed in-process
    against the step programs' optimized HLO, and classified by
    ``jax.named_scope`` stage — the result lands in ``DIR/devprof.json``,
    ``totals.devprof``, the metrics JSONL, and the ``/metrics`` gauges.
    Single-controller capture only (the CLI refuses ``--distributed``).
    """

    out_dir: str
    steps: int = 16
    warmup: int = 3

    def __post_init__(self) -> None:
        if not self.out_dir:
            raise ValueError("devprof out_dir must be non-empty")
        if not 1 <= self.steps <= 4096:
            raise ValueError(
                f"devprof steps must be in 1..4096, got {self.steps}"
            )
        if not 0 <= self.warmup <= 4096:
            raise ValueError(
                f"devprof warmup must be in 0..4096, got {self.warmup}"
            )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Configuration of the always-on ``serve`` mode (runtime/serve.py).

    Exactly one of ``window_lines`` / ``window_sec`` must be positive:
    line-count windows are deterministic and replayable (tests, soak
    benches — the same traffic always cuts at the same boundary),
    wall-clock windows are the production cadence ("unused in the last
    24h" = merge the last ``86400/window_sec`` ring epochs).
    """

    #: listener specs: ``udp:HOST:PORT``, ``tcp:HOST:PORT``, ``tail:PATH``
    listen: tuple[str, ...] = ()
    window_lines: int = 0  # rotate after N received lines (deterministic)
    window_sec: float = 0.0  # rotate on a wall-clock cadence (production)
    ring: int = 8  # window epochs retained for merged views
    #: merged views (in windows) re-published at every rotation, e.g.
    #: (24, 168) for 24h/7d at a 1h window
    views: tuple[int, ...] = ()
    queue_lines: int = 1 << 16  # listener queue capacity (drops counted past it)
    http: str = "127.0.0.1:0"  # JSON endpoint bind; "off" disables
    serve_dir: str = os.path.join(OUTPUT_DIR, "serve")
    #: ring checkpoint cadence in windows (0 = never); dir defaults to
    #: ``serve_dir/ckpt`` when empty
    checkpoint_every_windows: int = 1
    checkpoint_dir: str = ""
    reload_watch: bool = True  # poll the ruleset files; SIGHUP always works
    reload_poll_sec: float = 2.0
    max_windows: int = 0  # stop after N rotations (0 = run forever)
    stop_after_sec: float = 0.0  # soft wall deadline (0 = none); bounds tests
    #: run the static ruleset analyzer (runtime/staticanalysis.py) at
    #: start and on every hot reload (unchanged ACLs reuse their
    #: verdicts); publishes /report/static and joins evidence classes
    #: into every window report.  Off by default: reports stay
    #: bit-identical to the analysis-free service.
    static_analysis: bool = False
    #: per-rule witness-grid enumeration cap for the serve analyzer
    static_witness_budget: int = 4096
    #: durable ingest write-ahead log (runtime/wal.py, DESIGN §19):
    #: every consumed line appends to a segmented, CRC'd on-disk spool
    #: BEFORE window accounting, so ``serve --resume`` after a hard kill
    #: replays the interrupted window bit-identical over its delivered
    #: lines.  Off by default (the pre-WAL behavior: a hard kill loses
    #: lines buffered past the last checkpoint).
    wal: bool = False
    #: WAL directory (empty = ``serve_dir/wal``)
    wal_dir: str = ""
    #: bytes per WAL segment before rolling to a fresh one
    wal_segment_bytes: int = 1 << 20
    #: total on-disk WAL budget; exceeding it evicts the OLDEST segment,
    #: and evicted-but-unreplayed records surface as explicit, exactly-
    #: counted drops at the next resume (never a silent gap)
    wal_budget_bytes: int = 64 << 20
    #: window provenance plane (DESIGN §24): every published window
    #: carries a sealed ``totals.lineage`` record, appends it to
    #: ``serve_dir/lineage.jsonl``, and serves it on ``/lineage``.  On
    #: by default — provenance is the audit trail the reports exist for;
    #: ``--lineage off`` is the disarm knob the overhead bench compares
    #: against.
    lineage: bool = True
    #: SLO policy spec (runtime/metrics.py::SloPolicy), e.g.
    #: ``"p99_publish_ms<=500,drop_rate<=0.001"``; empty = no SLO
    #: engine.  Breach/recovery fire on multi-window burn-rate
    #: transitions, never per-window.
    slo: str = ""
    #: per-rule trend hysteresis ratio: a rule's window-over-window hit
    #: RATE rising past ``threshold``x (or collapsing below 1/x)
    #: publishes one typed ``rule_burst``/``rule_quiet`` event into
    #: diff.json + the flight recorder.  Must be > 1; 0 disables.
    trend_threshold: float = 4.0
    #: durable epoch store directory (runtime/epochstore.py, DESIGN
    #: §25): every rotated window spills here and background compaction
    #: keeps power-of-two summary nodes, so ``/report/range?from=&to=``
    #: answers any ``[t0,t1]`` report from O(log n) stored aggregates —
    #: replay-free — and ``/report/last-hit`` cites each rule's quiet
    #: horizon.  Empty = off (the ring stays the only history).
    epoch_store: str = ""
    #: total on-disk budget for the epoch store; exceeding it evicts
    #: the OLDEST raw-epoch segment first (coarse summaries still
    #: answer aligned queries over the evicted span), and an evicted
    #: range answers a typed ``range_incomplete`` — never silent zeros
    epoch_store_budget_bytes: int = 512 << 20

    def __post_init__(self) -> None:
        if (self.window_lines > 0) == (self.window_sec > 0):
            raise ValueError(
                "exactly one of window_lines/window_sec must be positive "
                f"(got lines={self.window_lines}, sec={self.window_sec})"
            )
        if self.window_lines < 0 or self.window_sec < 0:
            raise ValueError("window length must be positive")
        if self.ring < 1:
            raise ValueError(f"ring must be >= 1, got {self.ring}")
        if self.queue_lines < 1:
            raise ValueError(f"queue_lines must be >= 1, got {self.queue_lines}")
        if any(v < 1 for v in self.views):
            raise ValueError("views must be >= 1 window each")
        if any(v > self.ring for v in self.views):
            # a merged-24 view over an 8-epoch ring would claim 24
            # windows of evidence while holding 8 — refuse, don't shrink
            raise ValueError(
                f"views {tuple(v for v in self.views if v > self.ring)} "
                f"exceed the ring ({self.ring} windows retained); raise "
                "--ring or lower --view"
            )
        if self.checkpoint_every_windows < 0:
            raise ValueError("checkpoint_every_windows must be >= 0")
        if self.reload_poll_sec <= 0:
            raise ValueError("reload_poll_sec must be > 0")
        if self.max_windows < 0 or self.stop_after_sec < 0:
            raise ValueError("max_windows/stop_after_sec must be >= 0")
        if self.static_witness_budget < 1:
            raise ValueError(
                f"static_witness_budget must be >= 1, got "
                f"{self.static_witness_budget}"
            )
        if self.wal_segment_bytes < 4096:
            raise ValueError(
                f"wal_segment_bytes must be >= 4096, got "
                f"{self.wal_segment_bytes}"
            )
        if self.wal_budget_bytes < 2 * self.wal_segment_bytes:
            # the budget must hold at least the rolling segment plus one
            # sealed predecessor, or every roll would immediately evict
            raise ValueError(
                "wal_budget_bytes must be >= 2 * wal_segment_bytes "
                f"(got {self.wal_budget_bytes} vs segment "
                f"{self.wal_segment_bytes})"
            )
        if (self.wal_dir or self.wal_segment_bytes != 1 << 20
                or self.wal_budget_bytes != 64 << 20) and not self.wal:
            raise ValueError(
                "wal_dir/wal_segment_bytes/wal_budget_bytes require wal=True "
                "(serve --wal)"
            )
        if self.epoch_store_budget_bytes < 1 << 20:
            raise ValueError(
                "epoch_store_budget_bytes must be >= 1 MiB, got "
                f"{self.epoch_store_budget_bytes}"
            )
        if self.epoch_store_budget_bytes != 512 << 20 and not self.epoch_store:
            raise ValueError(
                "epoch_store_budget_bytes requires epoch_store "
                "(serve --epoch-store DIR)"
            )
        if self.trend_threshold != 0 and self.trend_threshold <= 1.0:
            raise ValueError(
                "trend_threshold must be > 1 (a multiplicative rate "
                f"band) or 0 to disable, got {self.trend_threshold}"
            )
        if self.slo:
            # parse errors surface at config time as the documented
            # ValueError class, not mid-serve
            from .runtime.metrics import SloPolicy

            SloPolicy.parse(self.slo)
        if self.http != "off":
            host, _, port = self.http.rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(
                    f"http must be HOST:PORT or 'off', got {self.http!r}"
                )


@dataclasses.dataclass(frozen=True)
class DistServeConfig:
    """Configuration of the multi-host distributed serve tier
    (runtime/distserve.py, DESIGN §22).

    ``serve --distributed`` runs one ingest worker per *host*: each host
    owns its own listeners, LineQueue, feeder tier, WAL spool, and
    flight-recorder ring, and accumulates windows into host-local
    register planes.  At every window rotation each host ships its
    epoch to rank 0 over the host-tier control plane (the ``("dcn",
    data)`` axis realized host-side: loopback TCP between processes on
    one machine, DCN between machines), where the epochs merge under
    the ``_merge_tail`` laws (add64/add32/max) — bit-identical to a
    single-host replay of the union of all hosts' delivered lines.
    Rank 0 owns publication (window/cumulative/diff JSON + HTTP).

    The host ladder runs ``min_hosts..max_hosts``; the ring-checkpoint
    fingerprint pins ``max_hosts`` (the ladder maximum, PR 7's divisor
    discipline lifted to the host tier) so a checkpoint taken at any
    world size resumes at any other.
    """

    #: number of ingest hosts to start with
    hosts: int = 2
    #: host-tier autoscale ladder bounds (actuated only when the serve
    #: run also passes --autoscale; max_hosts always pins the
    #: checkpoint fingerprint)
    min_hosts: int = 1
    max_hosts: int = 0  # 0 = hosts (no headroom to scale out into)
    #: worker isolation: "process" (true multi-core scaling, the
    #: production mode) or "thread" (in-process workers sharing one
    #: device pool — the deterministic test mode)
    workers: str = "process"
    #: rank-0 merge-plane bind (port 0 = ephemeral, recorded in
    #: serve_dir/endpoint.json)
    merge_bind: str = "127.0.0.1:0"
    #: how long rank 0 waits for a LIVE host's epoch before publishing
    #: the window without it (the window is then marked incomplete
    #: naming the missing host — never a hang, never a silent zero-hit)
    merge_timeout_sec: float = 120.0
    #: respawn a host that died unexpectedly (SIGKILL, OOM): the new
    #: process replays its predecessor's WAL tail past the last merged
    #: seq, so the rejoined host loses nothing that was spooled
    respawn: bool = False
    #: supervisor-lease TTL (DESIGN §23): the holder self-fences when it
    #: cannot renew within this long; a successor steals only after
    #: 1.5x, so takeover completes within ~2x TTL and the stale
    #: supervisor provably stops publishing first.  0 disables the
    #: whole lease/failover plane (single-supervisor PR 17 behaviour —
    #: the bench A/B leg and an operational escape hatch).
    lease_ttl_sec: float = 2.0
    #: where the durable per-host epoch spools + the lease live; ""
    #: places them under serve_dir (host-<rank>/spool and lease/).  Set
    #: this to shared storage so a successor on another machine can
    #: replay every host's spooled epochs.
    spool_dir: str = ""
    #: per-host epoch-spool disk budget (oldest segments evicted first,
    #: eviction counted — never silent); 0 disables spooling (epochs
    #: then survive only inside the supervisor's pending map)
    spool_budget_mb: int = 64

    def __post_init__(self) -> None:
        if self.hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {self.hosts}")
        if self.min_hosts < 1:
            raise ValueError(f"min_hosts must be >= 1, got {self.min_hosts}")
        if self.max_hosts < 0:
            raise ValueError(f"max_hosts must be >= 0, got {self.max_hosts}")
        eff_max = self.max_hosts or self.hosts
        if not self.min_hosts <= self.hosts <= eff_max:
            raise ValueError(
                f"hosts {self.hosts} must lie within "
                f"[min_hosts {self.min_hosts}, max_hosts {eff_max}]"
            )
        if self.workers not in ("process", "thread"):
            raise ValueError(
                f"workers must be 'process' or 'thread', got {self.workers!r}"
            )
        host, _, port = self.merge_bind.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"merge_bind must be HOST:PORT, got {self.merge_bind!r}"
            )
        if self.merge_timeout_sec <= 0:
            raise ValueError(
                f"merge_timeout_sec must be > 0, got {self.merge_timeout_sec}"
            )
        if self.lease_ttl_sec < 0:
            raise ValueError(
                f"lease_ttl_sec must be >= 0 (0 disables the lease plane), "
                f"got {self.lease_ttl_sec}"
            )
        if self.spool_budget_mb < 0:
            raise ValueError(
                f"spool_budget_mb must be >= 0 (0 disables spooling), "
                f"got {self.spool_budget_mb}"
            )

    @property
    def ladder_max(self) -> int:
        """The host-tier ladder maximum the checkpoint fingerprint pins."""
        return self.max_hosts or self.hosts

    def to_dict(self) -> dict:
        """JSON-serializable image (supervisor -> spawned worker handoff)."""
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "DistServeConfig":
        return DistServeConfig(**d)


@dataclasses.dataclass(frozen=True)
class AnalysisConfig:
    """Everything the runtime needs to run one analysis job."""

    backend: str = "tpu"  # {"oracle", "tpu"}
    batch_size: int = 1 << 16  # log lines per device step (per global batch)
    sketch: SketchConfig = dataclasses.field(default_factory=SketchConfig)
    exact_counts: bool = True  # keep the exact per-rule bincount alongside sketches
    #: Ceiling on total device register memory (counts + CMS + per-key HLL
    #: + talker CMS).  The per-key HLL file is the dangerous term —
    #: ``n_keys * 2**hll_p * 4`` bytes grows with the ruleset — so
    #: init_state refuses geometries that exceed this, with a suggested
    #: smaller ``hll_p``, instead of silently OOMing the chip.
    register_memory_budget_bytes: int = 4 << 30
    mesh_axis: str = "data"
    #: Mesh topology: "flat" = one data axis over every device (the
    #: historical shape); "hybrid" = the two-level DCN x ICI idiom
    #: (SNIPPETS.md [2] ``create_hybrid_device_mesh``): an outer "dcn"
    #: axis of ``mesh_dcn`` groups (hosts, once world size grows past
    #: one) times an inner ICI axis.  Batches shard over BOTH axes and
    #: every register merge reduces over both, so reports are
    #: bit-identical to the flat mesh — pinned on CPU as 2x4 vs flat 8.
    mesh_shape: str = "flat"
    #: Outer (DCN) extent of the hybrid mesh; 0 = auto (the process
    #: count when multi-process, else 2 — the CPU exercise geometry).
    mesh_dcn: int = 0
    checkpoint_every_chunks: int = 0  # 0 = no checkpointing
    checkpoint_dir: str = os.path.join(OUTPUT_DIR, "ckpt")
    resume: bool = False  # resume from checkpoint_dir if a snapshot exists
    report_every_chunks: int = 0  # 0 = no periodic throughput lines on stderr
    seed: int = 0
    #: First-match kernel implementation: "xla" (fused predicate, default),
    #: "pallas" (explicit-layout TPU kernel, ops/pallas_match.py), or
    #: "pallas_fused" (match + in-VMEM count histograms in one kernel,
    #: ops/pallas_fused.py — replaces the batch-sized exact-counts scatter
    #: with a row-sized one).  ``bench_suite.py pallas`` compares all
    #: three on the deployment hardware.
    match_impl: str = "xla"
    #: Exact-counts formulation: "scatter" (segment-sum scatter-add,
    #: default), "matmul" (one-hot matmul on the MXU), or "reduce"
    #: (compare-and-reduce on the VPU).  All bit-identical
    #: (ops/counts.py); ``bench_suite.py stage`` prices them on the
    #: deployment hardware — the TPU trace shows the scatter at 9.2 ms of
    #: a 60 ms step, so flipping this is a measured-default candidate.
    counts_impl: str = "scatter"
    #: Register-update formulation (DESIGN §15): "scatter" (five
    #: batch-sized scatter-add/scatter-max updates per step — the
    #: historical path) or "sorted" (sort the batch's register keys once
    #: with lax.sort, then segment-sum / segment-max over the sorted
    #: runs — the MapReduce-combiner sort half, ops/sorted_update.py).
    #: Bit-identical reports either way (uint32 add/max associativity);
    #: ``bench_suite.py stepvariants`` prices both on the deployment
    #: hardware.  Composes with counts_impl (matmul/reduce counts are
    #: already scatter-free and keep their formulation) and with
    #: coalesced/weighted inputs (the sorted updates are weight-linear
    #: by construction).
    update_impl: str = "scatter"
    #: Batch layout: "flat" scans every line against the whole rule
    #: tensor; "stacked" buckets lines by ACL host-side (pack.GroupBuffer)
    #: and vmaps the match over per-ACL rule slabs — O(max slab rows)
    #: per line instead of O(total rows) (BASELINE.json config #4).
    #: Registers are mergeable, so reports agree between layouts.
    layout: str = "flat"
    #: Per-ACL lane width of a stacked grouped batch; 0 = auto
    #: (~batch_size / n_acls, padded to the mesh).
    stacked_lane: int = 0
    #: Bounded prefetch depth of the pipelined ingest engine
    #: (runtime/ingest.py): a background producer parses / packs / issues
    #: the async device_put for up to this many batches ahead of the
    #: device step, so host parse and H2D overlap compute.  Reports stay
    #: bit-identical to the synchronous driver (batches commit in order).
    #: 0 = synchronous (the pre-pipelined driver); 2 = triple buffering.
    prefetch_depth: int = 2
    #: Watchdog bound (seconds) on a pipeline stage making NO progress:
    #: the prefetch consumer waiting on an empty queue and the feed
    #: coordinators waiting on worker completions escalate to a typed
    #: StallError after this long instead of wedging forever.  Progress
    #: resets the window, so legitimately slow inputs only need to
    #: advance once per window (CLI --stall-timeout; env
    #: RA_STALL_TIMEOUT overrides the default for bare library calls).
    stall_timeout_sec: float = 300.0
    #: Flow coalescing (runtime/coalesce.py): pre-aggregate each batch's
    #: duplicate evaluation tuples into (unique row, weight) pairs before
    #: the device step — the MapReduce-combiner move applied to a
    #: scatter-bound step.  Registers update weight-linearly (or
    #: idempotently, HLL), so reports are bit-identical to the
    #: uncoalesced path while device rows, scatters, and H2D bytes
    #: shrink by the traffic's compaction ratio.  "off" = never (the
    #: historical path, zero added work), "on" = always, "auto" =
    #: sample the first batches and disable below the break-even ratio.
    #: Applies to the single-process stream drivers; the distributed
    #: driver rejects it (per-process unique counts diverge, and the
    #: collective batch assembly needs one global shape).
    coalesce: str = "off"
    #: Serialized fault-injection schedule (runtime/faults.py;
    #: ``"site@N,site@N:k,seed=S"`` — the ``:k`` transient form fires k
    #: consecutive times then clears).  Empty = every site disarmed (the
    #: production state: one None-check per site).  Armed by the drivers
    #: at run start and exported to RA_FAULT_PLAN so spawned workers
    #: (feeder processes, elastic generations) inherit the schedule.
    fault_plan: str = ""
    #: Flight-recorder crash-forensics directory (runtime/flightrec.py,
    #: DESIGN §20).  Non-empty = the always-on in-memory telemetry ring
    #: is armed for this run and a typed abort / stall / unhandled crash
    #: dumps per-PID shards here, merged into ``postmortem.json``
    #: (exported to RA_BLACKBOX_DIR so spawned workers participate).
    #: Empty = disarmed (the bare-library default; the CLI defaults it
    #: to a ``blackbox`` dir beside the checkpoint/serve dir).
    blackbox_dir: str = ""
    #: Retry-policy overrides (runtime/retrypolicy.py, DESIGN §19;
    #: ``"site=attempts[/base_sec],...,seed=S"`` or ``"off"``).  Empty =
    #: the built-in per-site defaults (retries are always armed; this
    #: only tunes them).  Validated at configure time like fault_plan,
    #: so bad specs fail loudly at run start rather than silently at the
    #: first transient fault.
    retry_policy: str = ""

    def __post_init__(self) -> None:
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {self.batch_size}")
        if self.checkpoint_every_chunks < 0:
            raise ValueError("checkpoint_every_chunks must be >= 0")
        if self.match_impl not in ("xla", "pallas", "pallas_fused"):
            raise ValueError(
                "match_impl must be 'xla', 'pallas', or 'pallas_fused', "
                f"got {self.match_impl!r}"
            )
        if self.counts_impl not in ("scatter", "matmul", "reduce"):
            raise ValueError(
                "counts_impl must be 'scatter', 'matmul', or 'reduce', "
                f"got {self.counts_impl!r}"
            )
        if self.update_impl not in ("scatter", "sorted"):
            raise ValueError(
                "update_impl must be 'scatter' or 'sorted', "
                f"got {self.update_impl!r}"
            )
        if self.update_impl == "sorted" and self.match_impl == "pallas_fused":
            # the fused kernel computes its count histogram in-kernel
            # (its own scatter tail), so the sorted counts formulation
            # would silently never run — and the kernel is not
            # weight-linear, so the combination is unsafe for the
            # weighted inputs the sorted path exists to serve
            raise ValueError(
                "update_impl='sorted' is incompatible with the "
                "experimental match_impl='pallas_fused' (the fused kernel "
                "builds counts in-VMEM with its own scatter tail); use "
                "the default match_impl"
            )
        if self.match_impl == "pallas_fused" and self.counts_impl != "scatter":
            # the fused kernel produces the counts delta itself (in-VMEM
            # histograms), so a non-default counts_impl would silently
            # never run — reject the combination instead of mis-measuring
            raise ValueError(
                "match_impl='pallas_fused' computes counts in-kernel; "
                f"counts_impl={self.counts_impl!r} would be ignored — "
                "leave it 'scatter' (the default)"
            )
        if self.layout not in ("flat", "stacked"):
            raise ValueError(f"layout must be 'flat' or 'stacked', got {self.layout!r}")
        if self.mesh_shape not in ("flat", "hybrid"):
            raise ValueError(
                f"mesh_shape must be 'flat' or 'hybrid', got {self.mesh_shape!r}"
            )
        if self.mesh_dcn < 0:
            raise ValueError(f"mesh_dcn must be >= 0, got {self.mesh_dcn}")
        if self.mesh_dcn and self.mesh_shape != "hybrid":
            raise ValueError(
                "mesh_dcn only applies to mesh_shape='hybrid'"
            )
        if self.stacked_lane < 0:
            raise ValueError("stacked_lane must be >= 0")
        if not 0 <= self.prefetch_depth <= 1024:
            raise ValueError(
                f"prefetch_depth must be in 0..1024, got {self.prefetch_depth}"
            )
        if self.register_memory_budget_bytes < 1:
            raise ValueError("register_memory_budget_bytes must be >= 1")
        if self.stall_timeout_sec <= 0:
            raise ValueError(
                f"stall_timeout_sec must be > 0, got {self.stall_timeout_sec}"
            )
        if self.layout == "stacked" and self.match_impl != "xla":
            raise ValueError(
                f"match_impl={self.match_impl!r} supports layout='flat' only; "
                "the stacked path always uses the XLA vmapped kernel"
            )
        if self.coalesce not in ("off", "on", "auto"):
            raise ValueError(
                f"coalesce must be 'off', 'on', or 'auto', got {self.coalesce!r}"
            )
        if self.coalesce != "off":
            # the ONE weighted-input compatibility table (module top):
            # coalesced batches reach the step weighted, so every
            # registered incompatibility refuses here at config time.
            # (The stream drivers apply the same table to weighted
            # .rawire inputs, which this config-time check cannot see.)
            for r in WEIGHTED_INPUT_REFUSALS:
                if getattr(self, r.field) != r.value:
                    continue
                if (
                    r.coalesce_min_batch is not None
                    and self.batch_size < r.coalesce_min_batch
                ):
                    continue
                raise ValueError(
                    f"coalesce is incompatible with "
                    f"{r.field}={r.value!r}: {r.reason}"
                )

    def replace(self, **kw) -> "AnalysisConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        """JSON-serializable image (elastic supervisor -> worker handoff)."""
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "AnalysisConfig":
        """Inverse of :meth:`to_dict`; validation re-runs in __post_init__."""
        d = dict(d)
        d["sketch"] = SketchConfig(**d["sketch"])
        return AnalysisConfig(**d)
