"""Command-line interface — the job-submission layer (SURVEY.md §4.2).

The reference drives everything through three entry points: the parser
script (``getaccesslists.py``), a Hadoop Streaming submission wrapper
(``runAnalysis.sh``), and the report step.  This CLI is the single
replacement for all three:

  ruleset-analyze parse-acls CONFIG [CONFIG...] --out PREFIX
  ruleset-analyze run --ruleset PREFIX --logs FILE --backend {oracle,tpu}
  ruleset-analyze synth --out-dir DIR [...]

``--backend=oracle`` is the exact pure-Python path (the Hadoop-semantics
stand-in); ``--backend=tpu`` dispatches the hot loop to JAX (the reference
north star's ``--backend=tpu``).
"""

from __future__ import annotations

import argparse
import sys

from . import errors
from .config import AnalysisConfig, DevprofConfig, SketchConfig
from .hostside import aclparse, oracle, pack, synth
from .runtime import report as report_mod


def _report_ruleset(label: str, rs) -> None:
    """One parsed ruleset's summary + lenient-mode skips, to stderr."""
    skipped = f" skipped={len(rs.skipped)}" if rs.skipped else ""
    print(
        f"{label}: firewall={rs.firewall} acls={len(rs.acls)} "
        f"rules={rs.rule_count()} expanded_aces={rs.ace_count()}{skipped}",
        file=sys.stderr,
    )
    for lineno, reason, line in rs.skipped:
        print(f"{label}:{lineno}: skipped: {reason}: {line}", file=sys.stderr)


def _pack_and_save(rulesets, out_prefix: str, origin: str = "") -> int:
    packed = pack.pack_rulesets(rulesets)
    pack.save_packed(packed, out_prefix)
    print(
        f"packed {packed.rules.shape[0]} ACE rows, {packed.n_rules} rule keys, "
        f"{packed.n_acls} ACLs{origin} -> {out_prefix}.npz/.json",
        file=sys.stderr,
    )
    return 0


def _cmd_parse_acls(args: argparse.Namespace) -> int:
    rulesets = []
    for path in args.configs:
        rs = aclparse.parse_config_file(path, strict=not args.lenient)
        _report_ruleset(path, rs)
        rulesets.append(rs)
    return _pack_and_save(rulesets, args.out)


def _cmd_fetch_acls(args: argparse.Namespace) -> int:
    """getaccesslists.py analog: inventory -> fetch -> parse -> pack."""
    from .hostside import acquire

    inventory = acquire.load_inventory(args.inventory)
    if not inventory:
        print(
            "error: empty inventory (populate config.FIREWALLS or pass "
            "--inventory FILE with 'name = source' lines)",
            file=sys.stderr,
        )
        return 2
    rulesets = []
    for name, source, rs in acquire.iter_rulesets(
        inventory, strict=not args.lenient
    ):
        _report_ruleset(f"{name} <- {source}", rs)
        rulesets.append(rs)
    return _pack_and_save(
        rulesets, args.out, origin=f" from {len(rulesets)} firewalls"
    )


def _resolve_fault_plan(spec: str | None) -> str:
    """``--fault-plan`` value: a spec string, or ``@FILE`` naming a file
    holding one (chaos schedules checked into a repo).  Validated by
    parsing; the canonical form travels in the config."""
    if not spec:
        return ""
    from .runtime import faults

    if spec.startswith("@"):
        try:
            with open(spec[1:], "r", encoding="utf-8") as f:
                spec = f.read().strip()
        except OSError as e:
            # a bad plan FILE is a usage mistake like a bad plan string:
            # typed so the caller's handler exits 2, never a traceback
            raise errors.AnalysisError(
                f"cannot read fault plan file {spec[1:]!r}: {e}"
            ) from e
    return faults.FaultPlan.parse(spec).to_str()


#: --autoscale-X flag name -> AutoscaleConfig field.  The dataclass
#: field defaults are the ONE source of truth for flag defaults (both
#: the argparse defaults and the requires---autoscale check read them).
_AUTOSCALE_FIELDS = {
    "autoscale_min": "min_world",
    "autoscale_max": "max_world",
    "autoscale_initial": "initial_world",
    "autoscale_out_threshold": "out_threshold",
    "autoscale_in_threshold": "in_threshold",
    "autoscale_sustain": "sustain_sec",
    "autoscale_cooldown": "cooldown_sec",
    "autoscale_budget": "reform_budget",
    "autoscale_poll": "poll_sec",
    "autoscale_plan": "plan",
}


def _autoscale_defaults() -> dict:
    import dataclasses

    from .config import AutoscaleConfig

    by_field = {f.name: f.default for f in dataclasses.fields(AutoscaleConfig)}
    return {flag: by_field[field] for flag, field in _AUTOSCALE_FIELDS.items()}


def _autoscale_config(args):
    """``--autoscale`` flag family -> AutoscaleConfig (None when off)."""
    if not args.autoscale:
        for flag, dflt in _autoscale_defaults().items():
            if getattr(args, flag) != dflt:
                raise errors.AnalysisError(
                    f"--{flag.replace('_', '-')} requires --autoscale"
                )
        return None
    from .config import AutoscaleConfig

    return AutoscaleConfig(
        **{
            field: getattr(args, flag)
            for flag, field in _AUTOSCALE_FIELDS.items()
        }
    )


def _add_autoscale_flags(p) -> None:
    d = _autoscale_defaults()
    p.add_argument("--autoscale", action="store_true",
                   help="arm the metrics-driven elastic autoscaler "
                        "(DESIGN §13): sustained producer-backpressure "
                        "scales device workers OUT, sustained starvation "
                        "scales IN, via planned re-formations from the "
                        "epoch checkpoints — decisions carry their "
                        "evidence in the trace/metrics planes")
    p.add_argument("--autoscale-min", type=int, default=d["autoscale_min"], metavar="W",
                   help="smallest world the policy may scale in to")
    p.add_argument("--autoscale-max", type=int, default=d["autoscale_max"], metavar="W",
                   help="largest world (0 = everything provisioned: all "
                        "devices for serve, the launcher pool for "
                        "--elastic)")
    p.add_argument("--autoscale-initial", type=int, default=d["autoscale_initial"], metavar="W",
                   help="starting world (0 = the smallest allowed)")
    p.add_argument("--autoscale-out-threshold", type=float,
                   default=d["autoscale_out_threshold"],
                   metavar="F",
                   help="scale OUT when the pressure signal holds >= F "
                        "over the sustain window (fraction of wall time "
                        "producer-backpressured / queue-saturated)")
    p.add_argument("--autoscale-in-threshold", type=float,
                   default=d["autoscale_in_threshold"],
                   metavar="F",
                   help="scale IN when the starvation signal holds >= F "
                        "over the sustain window")
    p.add_argument("--autoscale-sustain", type=float, default=d["autoscale_sustain"],
                   metavar="SEC",
                   help="a signal must hold this long before a decision")
    p.add_argument("--autoscale-cooldown", type=float, default=d["autoscale_cooldown"],
                   metavar="SEC",
                   help="dead time after every decision (flap damping)")
    p.add_argument("--autoscale-budget", type=int, default=d["autoscale_budget"], metavar="N",
                   help="scale re-formations allowed per run (0 = "
                        "observe-only: decisions with evidence, no "
                        "actuation); separate from --max-reforms, which "
                        "stays the FAILURE budget")
    p.add_argument("--autoscale-poll", type=float, default=d["autoscale_poll"], metavar="SEC",
                   help="metrics sampling cadence of the policy engine")
    p.add_argument("--autoscale-plan", default=d["autoscale_plan"], metavar="SPEC",
                   help="scripted decisions for drills/tests "
                        "('out@T,in@T': fire at T seconds, in order), "
                        "bypassing the signal thresholds")


def _arm_devprof(args) -> int | None:
    """Validate + arm the device attribution capture (``--devprof-out``).

    Returns an exit code on a usage error, None on success (including
    the disarmed default).  Shared by ``run`` and ``serve``.
    """
    if not args.devprof_out:
        if (
            args.devprof_steps != DevprofConfig.steps
            or args.devprof_warmup != DevprofConfig.warmup
        ):
            print(
                "--devprof-steps/--devprof-warmup require --devprof-out",
                file=sys.stderr,
            )
            return 2
        return None
    if getattr(args, "distributed", False) or getattr(args, "elastic", False):
        # single-controller capture only: the profiler window, the
        # HLO re-derivation, and the trace parse all cover ONE process;
        # a multi-process job would publish a summary silently missing
        # every other rank's device time (DESIGN §14)
        print(
            "--devprof-out is a single-controller capture and is "
            "incompatible with --distributed/--elastic; capture on a "
            "single-process run of the same geometry instead",
            file=sys.stderr,
        )
        return 2
    if getattr(args, "profile_dir", None):
        print(
            "--devprof-out and --profile-dir both drive jax.profiler "
            "(one trace session per process); pick one — devprof is the "
            "bounded window with semantic attribution, profile-dir the "
            "whole-run TensorBoard trace",
            file=sys.stderr,
        )
        return 2
    from .runtime import devprof

    try:
        dcfg = DevprofConfig(
            out_dir=args.devprof_out,
            steps=args.devprof_steps,
            warmup=args.devprof_warmup,
        )
        devprof.arm(dcfg.out_dir, steps=dcfg.steps, warmup=dcfg.warmup)
    except (ValueError, errors.AnalysisError, OSError) as e:
        print(f"error: cannot arm --devprof-out: {e}", file=sys.stderr)
        return 2
    return None


def _add_devprof_flags(p) -> None:
    p.add_argument("--devprof-out", default=None, metavar="DIR",
                   help="device attribution capture (DESIGN §14): arm "
                        "jax.profiler for a bounded window of device "
                        "steps after warmup, classify device time by "
                        "named semantic stage (ra.match/ra.counts/"
                        "ra.hll/...), and write DIR/devprof.json — also "
                        "folded into totals.devprof, the metrics JSONL "
                        "and the /metrics gauges; diff two captures "
                        "with tools/trace_diff.py (single-controller "
                        "runs only)")
    p.add_argument("--devprof-steps", type=int,
                   default=DevprofConfig.steps, metavar="N",
                   help="device dispatches to capture (default "
                        f"{DevprofConfig.steps})")
    p.add_argument("--devprof-warmup", type=int,
                   default=DevprofConfig.warmup, metavar="K",
                   help="dispatches to skip before the window opens, so "
                        "compile/cache warmup never pollutes the "
                        f"attribution (default {DevprofConfig.warmup})")


def _resolve_blackbox(args, default_dir: str) -> str:
    """``--blackbox``/``--blackbox-dir`` -> the armed directory ('' = off).

    Always-on by default (DESIGN §20): a production run needs no flag to
    get crash forensics.  ``--blackbox off`` disarms; ``RA_BLACKBOX=off``
    disarms only the DEFAULT (an explicit ``--blackbox-dir`` still arms
    — test harnesses set the env so incidental CLI invocations don't
    write forensics into the working tree).  Raises AnalysisError on the
    contradictory ``--blackbox off --blackbox-dir D``.
    """
    import os

    from .runtime import flightrec

    if args.blackbox == "off":
        if args.blackbox_dir:
            raise errors.AnalysisError(
                "--blackbox-dir contradicts --blackbox off (drop one)"
            )
        return ""
    if not args.blackbox_dir and os.environ.get(
        flightrec.KILL_SWITCH, ""
    ).strip().lower() in ("off", "0"):
        return ""
    return args.blackbox_dir or default_dir


def _iter_log_lines(paths: list[str]):
    for path in paths:
        if path == "-":
            yield from sys.stdin
        else:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                yield from f


def _cmd_run(args: argparse.Namespace) -> int:
    try:
        import os as _os

        # the flight recorder's default home is BESIDE the checkpoint
        # dir ("out/ckpt" -> "out/blackbox"): forensics live where the
        # run's other durable state already lives
        ckpt_dir = args.checkpoint_dir or AnalysisConfig.checkpoint_dir
        blackbox_dir = _resolve_blackbox(
            args,
            _os.path.join(_os.path.dirname(ckpt_dir) or ".", "blackbox"),
        ) if args.backend == "tpu" else ""
        cfg = AnalysisConfig(
            backend=args.backend,
            batch_size=args.batch_size,
            sketch=SketchConfig(
                cms_width=args.cms_width,
                cms_depth=args.cms_depth,
                hll_p=args.hll_p,
                topk_sample_shift=args.topk_sample_shift,
                topk_every=args.topk_every,
            ),
            exact_counts=args.exact_counts,
            register_memory_budget_bytes=args.register_budget_mb << 20,
            checkpoint_every_chunks=args.checkpoint_every,
            resume=args.resume,
            report_every_chunks=args.report_every,
            match_impl=args.experimental_match_impl or args.match_impl,
            counts_impl=args.counts_impl,
            update_impl=args.update_impl,
            layout=args.layout,
            stacked_lane=args.stacked_lane,
            prefetch_depth=args.prefetch_depth,
            stall_timeout_sec=args.stall_timeout,
            coalesce=args.coalesce,
            mesh_shape=args.mesh,
            mesh_dcn=args.mesh_dcn,
            fault_plan=_resolve_fault_plan(args.fault_plan),
            retry_policy=args.retry_policy,
            blackbox_dir=blackbox_dir,
            **({"checkpoint_dir": args.checkpoint_dir} if args.checkpoint_dir else {}),
        )
        if args.retry_policy:
            # validate eagerly: a malformed --retry-policy must be the
            # usage error here, not a failure at the first transient
            from .runtime import retrypolicy

            retrypolicy.parse_spec(args.retry_policy)
        autoscale = _autoscale_config(args)
    except (ValueError, errors.AnalysisError) as e:
        # AnalysisError here is a malformed --fault-plan/--retry-policy:
        # a config mistake, so the usage exit code — not a runtime
        # failure class
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not args.static_analysis and args.static_witness_budget != 4096:
        # the devprof dependent-flag convention: a budget without the
        # analysis would be silently ignored, not a smaller analysis
        print("error: --static-witness-budget requires --static-analysis",
              file=sys.stderr)
        return 2
    if args.static_analysis and args.static_witness_budget < 1:
        # fail BEFORE the (possibly hours-long) traffic run, not at the
        # post-run analysis step where the computed report would be lost
        print("error: --static-witness-budget must be >= 1", file=sys.stderr)
        return 2
    packed = pack.load_packed(args.ruleset)
    lines = _iter_log_lines(args.logs)

    if args.backend == "oracle":
        from .hostside.wire import is_wire_file

        if any(p != "-" and is_wire_file(p) for p in args.logs):
            print(
                "--backend=oracle reads text syslog; .rawire files only "
                "apply to --backend=tpu", file=sys.stderr,
            )
            return 2
        # These only plumb into the device stream driver; accepting them
        # silently would let a user believe an oracle run is checkpointed.
        tpu_only = {
            "--checkpoint-every": args.checkpoint_every,
            "--resume": args.resume,
            "--report-every": args.report_every,
            "--profile-dir": args.profile_dir,
            "--trace-out": args.trace_out,
            "--metrics-out": args.metrics_out,
            "--native-parse": args.native_parse,
            "--checkpoint-dir": args.checkpoint_dir,
            "--layout=stacked": args.layout != "flat",
            "--packed-input": args.packed_input,
            "--no-exact-counts": not args.exact_counts,
            "--feed-workers": args.feed_workers > 1,
            "--feed-mode=thread": args.feed_workers > 1 and args.feed_mode == "thread",
            "--feed-mode=ring": args.feed_mode == "ring",
            "--experimental-match-impl": bool(args.experimental_match_impl),
            "--elastic": args.elastic,
            "--fault-plan": bool(args.fault_plan),
            "--retry-policy": bool(args.retry_policy),
            "--coalesce": args.coalesce != "off",
            "--mesh=hybrid": args.mesh != "flat",
            "--autoscale": args.autoscale,
            "--devprof-out": bool(args.devprof_out),
            "--update-impl=sorted": args.update_impl != "scatter",
            "--topk-every": args.topk_every != 1,
            "--blackbox-dir": bool(args.blackbox_dir),
            "--blackbox=off": args.blackbox == "off",
        }
        # --prefetch-depth is deliberately NOT rejected: like
        # --batch-size it is a tpu-path tuning knob the oracle ignores,
        # and rejecting its off value (0) would be nonsense
        bad = [k for k, v in tpu_only.items() if v]
        if bad:
            print(
                f"{', '.join(bad)} only apply to --backend=tpu", file=sys.stderr
            )
            return 2
        # Exact path: rebuild Ruleset objects is not possible from packed form
        # alone; the oracle needs the original configs.
        if not args.acl_configs:
            print("--backend=oracle requires --acl-configs (original config files)", file=sys.stderr)
            return 2
        rulesets = [
            aclparse.parse_config_file(p, strict=not args.lenient)
            for p in args.acl_configs
        ]
        orc = oracle.Oracle(rulesets)
        res = orc.consume(lines)
        # render per family: oracle talker identities are (family, addr)
        # so a v6 source prints as a v6 literal, never a garbled quad
        talkers = {
            k: [
                (
                    aclparse.int_to_ip6(s) if f == 6 else aclparse.u32_to_ip(s),
                    c,
                )
                for (f, s), c in cnt.most_common(args.topk)
            ]
            for k, cnt in res.talkers.items()
        }
        rep = report_mod.build_report(
            packed,
            dict(res.hits),
            backend="oracle",
            totals={
                "lines_total": res.lines_total,
                "lines_matched": res.lines_matched,
                "lines_skipped": res.lines_skipped,
            },
            unique_sources={k: len(v) for k, v in res.sources.items()},
            talkers=talkers,
        )
    elif args.backend == "tpu":
        try:
            from .runtime.compcache import enable_persistent_cache
            from .runtime.stream import (  # deferred: imports JAX
                run_stream,
                run_stream_file,
                run_stream_wire,
            )
        except ImportError as e:
            print(f"error: tpu backend unavailable ({e})", file=sys.stderr)
            return 1
        enable_persistent_cache()  # skip the ~15s recompile on repeat runs
        # convert-fleet manifests expand to their shard lists first: the
        # multi-file WireReader concatenates shard payloads and counts
        # resume offsets in stored-row units, so a fleet output is one
        # corpus from here on
        from .hostside.convertfleet import expand_wire_inputs

        args.logs = expand_wire_inputs(args.logs)
        file_input = all(p != "-" for p in args.logs)
        from .hostside.wire import is_wire_file

        # '-' (stdin) is never a wire file but still poisons a mix: binary
        # wire data must not fall through to the text-parse path
        n_wire = sum(1 for p in args.logs if p != "-" and is_wire_file(p))
        if args.packed_input and n_wire < len(args.logs):
            print(
                "--packed-input: not every --logs file is a .rawire wire "
                "file (run `ruleset-analyze convert` first)", file=sys.stderr,
            )
            return 2
        if 0 < n_wire < len(args.logs):
            print("cannot mix .rawire and text inputs in one --logs list", file=sys.stderr)
            return 2
        wire_input = n_wire == len(args.logs) and n_wire > 0
        if wire_input and (args.native_parse or args.feed_workers > 1):
            print(
                "--native-parse/--feed-workers do not apply to packed "
                ".rawire inputs (there is no text parse)", file=sys.stderr,
            )
            return 2
        if args.native_parse and not file_input:
            print("--native-parse requires file inputs (not '-')", file=sys.stderr)
            return 2
        if args.feed_workers > 1 and (
            not file_input or args.distributed or args.native_parse is False
        ):
            print(
                "--feed-workers requires file inputs and the native parser, "
                "and is not available with --distributed", file=sys.stderr,
            )
            return 2
        if args.feed_mode == "ring" and args.feed_workers < 1:
            print(
                "--feed-mode ring needs --feed-workers N (the per-chip "
                "producer pool size)", file=sys.stderr,
            )
            return 2
        if args.feed_mode == "ring" and (
            not file_input or args.distributed or args.native_parse is False
            or wire_input
        ):
            print(
                "--feed-mode ring requires text file inputs and the native "
                "parser, and is not available with --distributed",
                file=sys.stderr,
            )
            return 2
        if args.trace_out or args.metrics_out:
            # Arm the observability plane (runtime/obs.py) for the whole
            # run: span shards land in --trace-out (exported via
            # RA_TRACE_DIR so spawned feeder/elastic workers write
            # sibling shards) and the metrics snapshotter appends JSONL
            # to --metrics-out.  main()'s finally merges/stops them even
            # when the run ends in a typed abort — that trace is exactly
            # the one worth keeping.
            from .runtime import obs

            try:
                if args.trace_out:
                    obs.start_trace(args.trace_out, role="main")
                if args.metrics_out:
                    obs.start_metrics(args.metrics_out, args.metrics_every)
                    # live device-memory headroom in every snapshot
                    # (HBM stats where supported, explicit nulls on CPU)
                    from .runtime.devprof import device_memory_gauges

                    obs.register_sampler("device_mem", device_memory_gauges)
            except OSError as e:
                # an unwritable trace dir / metrics file is a usage
                # mistake, reported like every other bad-path flag —
                # not a raw traceback
                print(
                    f"error: cannot open --trace-out/--metrics-out "
                    f"target: {e}", file=sys.stderr,
                )
                return 2
        if args.autoscale and not args.elastic:
            print(
                "--autoscale applies to `serve` and to `run --elastic` "
                "(the supervised tier that can re-form the world); a "
                "fixed-membership run has nothing to scale", file=sys.stderr,
            )
            return 2
        rc = _arm_devprof(args)
        if rc is not None:
            return rc
        if args.elastic:
            # Elastic tier: this process becomes a recovery SUPERVISOR
            # (runtime/elastic.py) — --logs is the FULL shard list, the
            # same on every launcher; the supervisor rendezvous elects a
            # coordinator, spawns the analysis workers, and re-forms the
            # cluster automatically when a peer dies.  Only the final
            # generation's reporting member prints/writes the report.
            if not args.distributed:
                print("--elastic requires --distributed", file=sys.stderr)
                return 2
            if not file_input or wire_input:
                print(
                    "--elastic requires text file shards (not '-' or "
                    ".rawire)", file=sys.stderr,
                )
                return 2
            if args.num_processes is None or args.process_id is None:
                print(
                    "--elastic requires --num-processes and --process-id "
                    "(the launcher membership)", file=sys.stderr,
                )
                return 2
            if args.coordinator:
                print(
                    "--elastic elects its own coordinator; drop "
                    "--coordinator", file=sys.stderr,
                )
                return 2
            if not args.elastic_dir:
                print(
                    "--elastic requires --elastic-dir (shared rendezvous "
                    "+ epoch-checkpoint directory)", file=sys.stderr,
                )
                return 2
            if not args.json:
                print(
                    "--elastic reports via the JSON result the workers "
                    "write; add --json", file=sys.stderr,
                )
                return 2
            if args.static_analysis:
                print(
                    "--static-analysis does not ride the --elastic "
                    "result relay; run the `analyze` subcommand against "
                    "the same --ruleset instead", file=sys.stderr,
                )
                return 2
            import json as json_mod
            import os as os_mod

            from .errors import AnalysisError as _AErr
            from .runtime import faults
            from .runtime.elastic import ElasticSupervisor

            fault = None
            fault_env = os_mod.environ.get("RA_ELASTIC_FAULT")
            if fault_env:
                # test-only crash injection: "tag=K,after_batches=M[,gen=G]"
                fault = dict(
                    kv.split("=", 1) for kv in fault_env.split(",")
                )
            try:
                sup = ElasticSupervisor(
                    args.elastic_dir,
                    args.process_id,
                    args.num_processes,
                    args.ruleset,
                    args.logs,
                    cfg,
                    max_reforms=args.max_reforms,
                    topk=args.topk,
                    native=args.native_parse,
                    out_prefix=os_mod.path.join(
                        args.elastic_dir, "result"
                    ),
                    fault=fault,
                    autoscale=autoscale,
                )
            except _AErr as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            # the supervisor process hosts fault sites of its own (the
            # autoscale decide/actuate seam); workers re-arm the same
            # spec idempotently from the job config.  The supervisor
            # also OWNS the blackbox dir: it arms first (pruning stale
            # shards), and the spawned generation workers join via the
            # exported RA_BLACKBOX_DIR without pruning.
            if cfg.blackbox_dir:
                from .runtime import flightrec as _flightrec

                _flightrec.arm(cfg.blackbox_dir, role="elastic-supervisor")
            armed_here = faults.arm_spec(cfg.fault_plan)
            try:
                rc, result_path = sup.run()
            except _AErr as e:
                # a typed runtime abort (e.g. an injected autoscale
                # fault at the decide/actuate seam) exits with its
                # documented failure-class code, never a traceback.
                # Note the abort so the finalize in main()'s finally
                # merges the generation workers' shards instead of
                # treating the return as a clean exit and pruning them.
                from .runtime import flightrec as _flightrec

                _flightrec.note_abort(e, errors.exit_code_for(e))
                print(f"error: {e}", file=sys.stderr)
                return errors.exit_code_for(e)
            finally:
                if armed_here:
                    faults.disarm()
            if rc != 0 or result_path is None:
                if rc != 0:
                    # a failure the supervisor reported by exit code
                    # alone (no exception reached us): the finalize in
                    # main()'s finally still merges the postmortem
                    from .runtime import flightrec as _flightrec

                    _flightrec.note_failure(rc)
                return rc
            with open(result_path, "r", encoding="utf-8") as f:
                rep_obj = json_mod.load(f)
            payload = json_mod.dumps(rep_obj, indent=2)
            if args.out:
                with open(args.out, "w", encoding="utf-8") as f:
                    f.write(payload + "\n")
            else:
                print(payload)
            return 0
        if args.distributed:
            # multi-process job: this process joins the cluster and feeds
            # only ITS OWN --logs (the input-split analog); every process
            # computes the identical report, only rank 0 prints it
            if not file_input:
                print("--distributed requires file inputs (not '-')", file=sys.stderr)
                return 2
            if args.coalesce != "off":
                print(
                    "--coalesce applies to single-process runs only; for "
                    "distributed jobs pre-coalesce the input with "
                    "`ruleset-analyze convert --coalesce`", file=sys.stderr,
                )
                return 2
            import jax

            from .parallel.distributed import init_distributed
            from .runtime.stream import run_stream_file_distributed

            init_distributed(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
            )
            rep = run_stream_file_distributed(
                packed, args.logs, cfg, native=args.native_parse, topk=args.topk
            )
            if jax.process_index() != 0:
                return 0
        elif wire_input:
            rep = run_stream_wire(
                packed,
                args.logs,
                cfg,
                topk=args.topk,
                profile_dir=args.profile_dir,
            )
        elif file_input:
            # forced --native-parse with no C++ toolchain raises
            # NativeParserUnavailable, handled as AnalysisError in main()
            rep = run_stream_file(
                packed,
                args.logs,
                cfg,
                native=args.native_parse,  # None = auto
                topk=args.topk,
                profile_dir=args.profile_dir,
                feed_workers=args.feed_workers,
                feed_mode=args.feed_mode,
            )
        else:
            rep = run_stream(packed, lines, cfg, topk=args.topk, profile_dir=args.profile_dir)
    else:
        print(f"unknown backend {args.backend!r}", file=sys.stderr)
        return 2

    if args.static_analysis:
        # join the static verdicts into the live-evidence report: the
        # whole run counted under this one ruleset, so a hit on a
        # provably-dead rule is a hard contradiction (strict=True ->
        # typed AnalyzerContradiction, handled by main()).  Strict only
        # with EXACT counters: under --no-exact-counts the per-rule
        # "hits" are CMS estimates, and a sketch collision can inflate a
        # dead rule's estimate above zero — annotate, don't abort.
        from .runtime import staticanalysis

        sa = staticanalysis.analyze_ruleset(
            packed, witness_budget=args.static_witness_budget
        )
        # (oracle runs always count exactly; --no-exact-counts is
        # rejected for that backend above)
        staticanalysis.attach_static(rep, packed, sa, strict=args.exact_counts)

    payload = rep.to_json() if args.json else rep.to_text()
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")
    else:
        print(payload)
    return 0


def _cmd_serve(args: argparse.Namespace) -> int:
    """Always-on service mode: live listeners -> windowed reports.

    Runs until --max-windows/--stop-after (or SIGINT); the window ring,
    report publication, reload semantics, and endpoint paths live in
    runtime/serve.py (DESIGN §12).
    """
    from .config import ServeConfig

    if not args.static_analysis and args.static_witness_budget != 4096:
        print("error: --static-witness-budget requires --static-analysis",
              file=sys.stderr)
        return 2
    if bool(args.ruleset) == bool(args.tenants):
        print("error: serve needs exactly one of --ruleset or "
              "--tenants MANIFEST", file=sys.stderr)
        return 2
    if not args.distributed:
        for flag, dflt in (
            ("dist_hosts", 2), ("dist_min_hosts", 1),
            ("dist_max_hosts", 0), ("dist_workers", "process"),
            ("dist_merge_bind", "127.0.0.1:0"),
            ("dist_merge_timeout", 120.0), ("dist_respawn", False),
            ("dist_lease_ttl", 2.0), ("dist_spool_dir", ""),
            ("dist_spool_budget_mb", 64),
        ):
            if getattr(args, flag) != dflt:
                print(f"error: --{flag.replace('_', '-')} requires "
                      "--distributed", file=sys.stderr)
                return 2
    try:
        import os as _os

        cfg = AnalysisConfig(
            backend="tpu",
            mesh_shape=args.mesh,
            batch_size=args.batch_size,
            sketch=SketchConfig(
                cms_width=args.cms_width,
                cms_depth=args.cms_depth,
                hll_p=args.hll_p,
                topk_every=args.topk_every,
            ),
            register_memory_budget_bytes=args.register_budget_mb << 20,
            resume=args.resume,
            stall_timeout_sec=args.stall_timeout,
            update_impl=args.update_impl,
            fault_plan=_resolve_fault_plan(args.fault_plan),
            retry_policy=args.retry_policy,
            # beside the serve dir, like the ring checkpoint (DESIGN §20)
            blackbox_dir=_resolve_blackbox(
                args, _os.path.join(args.serve_dir, "blackbox")
            ),
        )
        if args.retry_policy:
            from .runtime import retrypolicy

            retrypolicy.parse_spec(args.retry_policy)
        ascfg = _autoscale_config(args)
        mode, length = report_mod.parse_window_spec(args.window)
        scfg = ServeConfig(
            listen=tuple(args.listen),
            window_lines=int(length) if mode == "lines" else 0,
            window_sec=length if mode == "sec" else 0.0,
            ring=args.ring,
            views=tuple(args.view),
            queue_lines=args.queue_lines,
            http=args.http,
            serve_dir=args.serve_dir,
            checkpoint_every_windows=args.checkpoint_every_windows,
            checkpoint_dir=args.checkpoint_dir or "",
            reload_watch=args.reload_watch,
            reload_poll_sec=args.reload_poll,
            max_windows=args.max_windows,
            stop_after_sec=args.stop_after,
            static_analysis=args.static_analysis,
            static_witness_budget=args.static_witness_budget,
            wal=args.wal,
            wal_dir=args.wal_dir,
            wal_segment_bytes=args.wal_segment_kb << 10,
            wal_budget_bytes=args.wal_budget_mb << 20,
            lineage=args.lineage != "off",
            slo=args.slo,
            trend_threshold=args.trend_threshold,
            epoch_store=args.epoch_store,
            epoch_store_budget_bytes=args.epoch_store_budget_mb << 20,
        )
        dscfg = None
        if args.distributed:
            from .config import DistServeConfig

            dscfg = DistServeConfig(
                hosts=args.dist_hosts,
                min_hosts=args.dist_min_hosts,
                max_hosts=args.dist_max_hosts,
                workers=args.dist_workers,
                merge_bind=args.dist_merge_bind,
                merge_timeout_sec=args.dist_merge_timeout,
                respawn=args.dist_respawn,
                lease_ttl_sec=args.dist_lease_ttl,
                spool_dir=args.dist_spool_dir,
                spool_budget_mb=args.dist_spool_budget_mb,
            )
    except (ValueError, errors.AnalysisError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        from .runtime.serve import ServeDriver  # deferred: imports JAX
    except ImportError as e:
        print(f"error: tpu backend unavailable ({e})", file=sys.stderr)
        return 1
    if args.trace_out or args.metrics_out:
        from .runtime import obs

        try:
            if args.trace_out:
                obs.start_trace(args.trace_out, role="serve")
            if args.metrics_out:
                obs.start_metrics(args.metrics_out, args.metrics_every)
                from .runtime.devprof import device_memory_gauges

                obs.register_sampler("device_mem", device_memory_gauges)
        except OSError as e:
            print(
                f"error: cannot open --trace-out/--metrics-out target: {e}",
                file=sys.stderr,
            )
            return 2
    rc = _arm_devprof(args)
    if rc is not None:
        return rc
    try:
        # construction binds the listener sockets: a privileged port or
        # an address in use must be the documented clean error, not a
        # traceback
        if args.tenants:
            if ascfg is not None:
                print("error: --autoscale does not combine with --tenants "
                      "(the tenancy plane packs many rulesets onto one "
                      "fixed mesh)", file=sys.stderr)
                return 2
            from .runtime.tenantserve import TenantServeDriver

            try:
                driver = TenantServeDriver(
                    args.tenants, cfg, scfg, topk=args.topk,
                    distributed=dscfg,
                )
            except errors.AnalysisError as e:
                # bad manifest / unsupported combination (e.g. --resume
                # with --tenants): typed refusal, exit 2.  A bad
                # --ruleset stays on main()'s typed-load path (exit 1).
                print(f"error: {e}", file=sys.stderr)
                return 2
        elif args.distributed:
            from .runtime.distserve import DistServeDriver

            try:
                driver = DistServeDriver(
                    args.ruleset, cfg, scfg, dscfg,
                    topk=args.topk, ascfg=ascfg,
                )
            except errors.AnalysisError as e:
                # unsupported combination (--mesh flat, --static-analysis)
                # or an unreadable ruleset: typed refusal, exit 2
                print(f"error: {e}", file=sys.stderr)
                return 2
        else:
            driver = ServeDriver(
                args.ruleset, cfg, scfg, topk=args.topk, ascfg=ascfg
            )
    except OSError as e:
        print(f"error: cannot bind --listen/--http: {e}", file=sys.stderr)
        return 2
    try:
        summary = driver.run()
    except OSError as e:
        print(f"error: serve I/O failure: {e}", file=sys.stderr)
        return 1
    import json as json_mod

    print(json_mod.dumps(summary, indent=2))
    return 0


def _cmd_convert(args: argparse.Namespace) -> int:
    """Text syslog -> pre-tokenized .rawire wire file (SURVEY.md §8.2).

    Parses once (native C++ parser when available) and writes the 16 B/line
    bit-packed evaluation rows; `run` then feeds the device straight from
    the mmap'd file, skipping the host parse that bottlenecks e2e.
    """
    from .hostside import wire

    if args.block_rows < 1:
        print("error: --block-rows must be >= 1", file=sys.stderr)
        return 2
    from .hostside.convertfleet import is_manifest_file

    already = [
        p for p in args.logs if wire.is_wire_file(p) or is_manifest_file(p)
    ]
    if already:
        # a shell glob catching *.rawire must not "convert" binary data
        # through the text parser into a valid-but-empty wire file
        print(
            f"error: {already[0]!r} is already a wire file; convert takes "
            "text syslog inputs",
            file=sys.stderr,
        )
        return 2
    packed = pack.load_packed(args.ruleset)
    if args.workers and args.workers >= 1:
        # convert fleet (ISSUE 11): N processes, N pre-coalesced weighted
        # shards, one manifest at --out; byte-identical for any N
        from .hostside.convertfleet import convert_logs_fleet

        if args.native_parse is False:
            print("error: --workers requires the native parser", file=sys.stderr)
            return 2
        stats = convert_logs_fleet(
            packed,
            args.logs,
            args.out,
            workers=args.workers,
            # --block-rows doubles as the descriptor granularity: shards
            # split (and batches coalesce) at exact multiples of it, so
            # the stored stream is a pure function of (corpus, block-rows)
            batch_size=args.block_rows,
            block_rows=args.block_rows,
            coalesce=True,  # the fleet always writes the weighted format
        )
    else:
        stats = wire.convert_logs(
            packed,
            args.logs,
            args.out,
            native=args.native_parse,
            block_rows=args.block_rows,
            feed_workers=args.feed_workers,
            coalesce=args.coalesce,
        )
    mb = stats["bytes"] / 1e6
    if stats.get("weighted"):
        stored = stats["rows"] + stats["rows6"]
        ratio = stats["evals"] / max(stored, 1)
        shape = (
            f"{stored} weighted rows for {stats['evals']} evaluations "
            f"(compaction {ratio:.2f}x)"
        )
    else:
        shape = f"{stats['evals']} evaluation rows"
    print(
        f"wrote {args.out}: {shape}"
        f"{' (' + str(stats['rows6']) + ' v6)' if stats.get('rows6') else ''} from "
        f"{stats['raw_lines']} lines ({stats['skipped']} skipped), "
        f"{mb:.1f} MB, parser={stats['parser']}",
        file=sys.stderr,
    )
    return 0


def _cmd_wire_info(args: argparse.Namespace) -> int:
    """Inspect .rawire headers; optionally validate against a ruleset."""
    import json as json_mod

    from .hostside import wire
    from .hostside.convertfleet import expand_wire_inputs

    args.files = expand_wire_inputs(args.files)
    # hash the ruleset once, not once per file
    fp = (
        wire.ruleset_fingerprint(pack.load_packed(args.ruleset))
        if args.ruleset
        else None
    )
    rc = 0
    rows = []
    for path in args.files:
        try:
            r = wire.WireReader([path], fingerprint=fp)
        except (wire.WireFormatError, OSError) as e:
            rows.append({"file": path, "ok": False, "error": str(e)})
            rc = 1
            continue
        rows.append({
            "file": path,
            "ok": True,
            "rows": r.n_rows,
            "rows6": r.n6_rows,
            "raw_lines": r.raw_lines,
            "skipped_lines": r.n_skipped,
            "block_rows": r.block_rows,
            "bytes_per_row": wire.ROWW_BYTES if r.weighted else wire.ROW_BYTES,
            "weighted": r.weighted,
            # weighted (coalesced) files: true evaluation count behind
            # the stored unique rows
            **({"evals": r.n_evals} if r.weighted else {}),
            # null = no ruleset given, nothing was checked; a real
            # mismatch surfaces as ok=false with the fingerprint error
            "ruleset_match": True if fp is not None else None,
        })
        r.close()
    if args.json:
        print(json_mod.dumps(rows, indent=2))
    else:
        for e in rows:
            if e["ok"]:
                w = (
                    f" weighted rows ({e['evals']} evaluations)"
                    if e.get("weighted")
                    else " rows"
                )
                print(
                    f"{e['file']}: {e['rows']}{w}"
                    f"{' + ' + str(e['rows6']) + ' v6 rows' if e.get('rows6') else ''}"
                    f" from {e['raw_lines']} lines "
                    f"({e['skipped_lines']} skipped), block={e['block_rows']}"
                    + (", ruleset OK" if args.ruleset else "")
                )
            else:
                print(f"{e['file']}: INVALID — {e['error']}")
    return rc


def _cmd_analyze(args: argparse.Namespace) -> int:
    """Static ruleset analysis: which rules can NEVER get a hit.

    The dual of ``run``: no traffic at all — per-rule reachability
    verdicts from the packed rule tensor alone (runtime/staticanalysis),
    with every dead verdict carrying an exact single-rule cover or a
    complete witness-exhaustion record.
    """
    import json as json_mod

    from .runtime import faults, staticanalysis

    if args.witness_budget < 1:
        print("error: --witness-budget must be >= 1", file=sys.stderr)
        return 2
    if args.tile is not None and args.tile < 1:
        print("error: --tile must be >= 1", file=sys.stderr)
        return 2
    packed = pack.load_packed(args.ruleset)
    armed_here = faults.arm_spec(_resolve_fault_plan(args.fault_plan))
    try:
        sa = staticanalysis.analyze_ruleset(
            packed, tile=args.tile, witness_budget=args.witness_budget
        )
    finally:
        if armed_here:
            faults.disarm()
    obj = sa.to_obj(packed)
    payload = (
        json_mod.dumps(obj, indent=2)
        if args.json
        else staticanalysis.render_text(packed, obj)
    )
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")
    else:
        print(payload)
    return 0


def _cmd_lint(args: argparse.Namespace) -> int:
    """ralint: static program-invariant verification (DESIGN §18).

    Traces every shipping step program to a closed jaxpr by abstract
    eval (no device data, no XLA compile) and verifies weight-linearity,
    scatter safety, ra.* scope coverage, and merge-law conformance;
    cross-checks the derived weighted-refusal set against the ONE
    declarative table in config.py; audits the repo registries (fault
    sites / CLI flags vs docs / volatile totals keys).  Exit 0 = every
    invariant proven (or typed-refused), 1 = findings.
    """
    import json as json_mod

    from .verify import render_text, run_lint

    rep = run_lint(
        full=not args.fast,
        registry=not args.skip_registry,
        repo_root=args.repo_root,
    )
    if args.json:
        payload = json_mod.dumps(rep.to_dict(), indent=2)
    else:
        payload = render_text(rep)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")
    else:
        print(payload)
    return 0 if rep.ok else 1


def _cmd_diff_reports(args: argparse.Namespace) -> int:
    """Compare two JSON run reports: the operator's delete-decision view.

    The reference's end goal is "which rules can we safely delete"; one
    run can't answer that (a rule may simply be quiet this week).  This
    diff shows stability across runs: rules unused in BOTH reports are
    the deletion candidates, newly-unused / newly-used rules are the
    churn to investigate.
    """
    import json as json_mod

    if args.top < 0:
        print("error: --top must be >= 0", file=sys.stderr)
        return 2

    def load(path):
        with open(path, "r", encoding="utf-8") as f:
            return json_mod.load(f)

    try:
        rep_a, rep_b = load(args.old), load(args.new)
        if args.expect_window:
            # typed refusal: a 24h window diffed against a 7d window is a
            # misleading answer, not a smaller one (main() maps the code)
            report_mod.check_window_compat(rep_a, rep_b, args.expect_window)
        out = report_mod.diff_report_objs(rep_a, rep_b, top=args.top)
    except errors.AnalysisError:
        raise
    except (OSError, ValueError, KeyError, TypeError) as e:
        print(f"error: unreadable report: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json_mod.dumps(out, indent=2))
        return 0
    print(f"# stable unused (deletion candidates): {len(out['stable_unused'])}")
    for k in out["stable_unused"]:
        print(f"  {k}")
    print(f"# newly unused (quiet this run): {len(out['newly_unused'])}")
    for k in out["newly_unused"]:
        print(f"  {k}")
    print(f"# newly used (were unused before): {len(out['newly_used'])}")
    for k in out["newly_used"]:
        print(f"  {k}")
    if out["rules_added"] or out["rules_removed"]:
        print(
            f"# ruleset churn: {len(out['rules_added'])} added, "
            f"{len(out['rules_removed'])} removed between reports"
        )
    if out["top_hit_movers"]:
        print("# top hit movers:")
        for m in out["top_hit_movers"]:
            print(f"  {m['rule']}: {m['old']} -> {m['new']}")
    if out.get("verdict_transitions"):
        print(
            f"# static verdict transitions: {len(out['verdict_transitions'])}"
            " (a rule changing reachability class across a ruleset change)"
        )
        for m in out["verdict_transitions"]:
            print(f"  {m['rule']}: {m['old']} -> {m['new']}")
    if out.get("window_incomplete"):
        print(
            f"# WARNING: incomplete window(s): {', '.join(out['window_incomplete'])}"
            " — churn there may be drop artifacts, not traffic"
        )
    return 0


def _cmd_synth(args: argparse.Namespace) -> int:
    import os

    os.makedirs(args.out_dir, exist_ok=True)
    cfg_text = synth.synth_config(
        n_acls=args.acls, rules_per_acl=args.rules, seed=args.seed,
        hostname=args.hostname, v6_fraction=args.v6_fraction,
    )
    cfg_path = f"{args.out_dir}/{args.hostname}.cfg"
    with open(cfg_path, "w", encoding="utf-8") as f:
        f.write(cfg_text)
    rs = aclparse.parse_asa_config(cfg_text, args.hostname)
    packed = pack.pack_rulesets([rs])
    n6 = int(args.lines * args.v6_fraction) if packed.has_v6 else 0
    if args.flows > 0:
        # flow-repetition tier: Zipf(--skew) draws from a bounded flow
        # pool, the feedstock the coalescing ingest tier compacts
        tuples = synth.synth_flow_tuples(
            packed, args.lines - n6, args.flows, skew=args.skew,
            seed=args.seed,
        )
    else:
        tuples = synth.synth_tuples(packed, args.lines - n6, seed=args.seed)
    log_lines = synth.render_syslog(packed, tuples, seed=args.seed)
    if n6:
        import random as _random

        t6 = synth.synth_tuples6(packed, n6, seed=args.seed)
        log_lines = log_lines + synth.render_syslog6(packed, t6, seed=args.seed + 1)
        _random.Random(args.seed).shuffle(log_lines)
    log_path = f"{args.out_dir}/{args.hostname}.log"
    with open(log_path, "w", encoding="utf-8") as f:
        f.write("\n".join(log_lines) + "\n")
    pack.save_packed(packed, f"{args.out_dir}/{args.hostname}")
    print(f"wrote {cfg_path}, {log_path}, {args.out_dir}/{args.hostname}.npz", file=sys.stderr)
    return 0


def _add_blackbox_flags(p) -> None:
    p.add_argument("--blackbox", choices=["on", "off"], default="on",
                   help="always-on flight recorder (DESIGN §20): every "
                        "process keeps a bounded in-memory ring of recent "
                        "telemetry (spans, fault/retry/degraded instants, "
                        "metrics snapshots, commit cursors); a typed "
                        "abort, watchdog stall, unhandled crash, or "
                        "SIGQUIT dumps per-PID shards merged into "
                        "postmortem.json — a clean exit leaves nothing. "
                        "Default on (no per-event file I/O; <2%% budget, "
                        "BENCH_BLACKBOX artifact)")
    p.add_argument("--blackbox-dir", default=None, metavar="DIR",
                   help="crash-forensics directory (default: a 'blackbox' "
                        "dir beside the checkpoint/serve dir); exported "
                        "as RA_BLACKBOX_DIR so spawned feeder/elastic "
                        "workers dump sibling shards; diagnose a bundle "
                        "with `ruleset-analyze doctor`")


def _cmd_doctor(args: argparse.Namespace) -> int:
    """Postmortem bundle + exit code -> ranked human-readable diagnosis.

    The first-response runbook for exit codes 3-8: reads the
    ``postmortem.json`` a crashed run's flight recorder merged and names
    the failing stage, the fired fault sites, and the next action.
    """
    import json as json_mod

    from .runtime import flightrec

    try:
        bundle = flightrec.load_bundle(args.bundle)
    except (OSError, ValueError) as e:
        print(f"error: unreadable postmortem bundle: {e}", file=sys.stderr)
        return 1
    lpath = getattr(args, "lineage", None) or flightrec.find_lineage(args.bundle)
    lineage = flightrec.load_lineage(lpath) if lpath else []
    diags = flightrec.diagnose(
        bundle, exit_code=args.exit_code, lineage=lineage
    )
    if args.json:
        from .runtime.report import lineage_frontier

        payload = json_mod.dumps(
            {
                "trigger": bundle.get("trigger"),
                "exit_code": (
                    args.exit_code if args.exit_code is not None
                    else bundle.get("exit_code")
                ),
                "error": bundle.get("error"),
                "error_type": bundle.get("error_type"),
                "failing_stage": bundle.get("analysis", {}).get("failing_stage"),
                "lineage_path": lpath,
                "lineage_frontier": (
                    lineage_frontier(lineage) if lineage else None
                ),
                "diagnosis": diags,
            },
            indent=2,
        )
    else:
        payload = flightrec.render_diagnosis(bundle, diags)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")
    else:
        print(payload)
    return 0


def make_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ruleset-analyze")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("parse-acls", help="parse ASA configs into a packed ruleset")
    p.add_argument("configs", nargs="+")
    p.add_argument("--out", required=True, help="output path prefix")
    p.add_argument("--lenient", action="store_true",
                   help="skip (and count) unsupported access-list entries — "
                        "IPv6, exotic object members — instead of aborting; "
                        "skipped entries keep their rule positions")
    p.set_defaults(fn=_cmd_parse_acls)

    p = sub.add_parser(
        "fetch-acls",
        help="acquire + parse configs from a firewall inventory "
             "(config.FIREWALLS or --inventory)",
    )
    p.add_argument("--inventory", default=None, metavar="FILE",
                   help="'name = source' lines; source is a config file path "
                        "or cmd:<shell command> whose stdout is the config "
                        "(default: config.FIREWALLS). cmd: sources run "
                        "through the shell — the inventory file must be "
                        "trusted like a shell script")
    p.add_argument("--out", required=True, help="output path prefix")
    p.add_argument("--lenient", action="store_true",
                   help="skip-and-count unsupported entries (see parse-acls)")
    p.set_defaults(fn=_cmd_fetch_acls)

    p = sub.add_parser("run", help="run the analysis over syslog")
    p.add_argument("--ruleset", required=True, help="packed ruleset path prefix")
    p.add_argument("--logs", nargs="+", required=True, help="syslog file(s), '-' for stdin")
    p.add_argument("--backend", choices=["oracle", "tpu"], default="tpu")
    p.add_argument("--acl-configs", nargs="*", default=[], help="original configs (oracle backend)")
    p.add_argument("--lenient", action="store_true",
                   help="parse --acl-configs leniently (see parse-acls --lenient)")
    p.add_argument("--batch-size", type=int, default=1 << 16)
    p.add_argument("--cms-width", type=int, default=1 << 14)
    p.add_argument("--cms-depth", type=int, default=4)
    p.add_argument("--hll-p", type=int, default=8)
    p.add_argument("--exact-counts", action=argparse.BooleanOptionalAction, default=True,
                   help="--no-exact-counts drops the exact per-rule bincount and "
                        "reports CMS estimates instead (the BASELINE.json "
                        "north-star configuration: sketches only)")
    p.add_argument("--register-budget-mb", type=int, default=4096, metavar="MB",
                   help="ceiling on device register memory (counts+CMS+HLL); "
                        "oversized geometries fail fast with a suggested --hll-p")
    p.add_argument("--topk", type=int, default=10)
    p.add_argument("--topk-sample-shift", type=int, default=0, metavar="S",
                   help="select per-chunk talker candidates from every "
                        "2^S-th line (the talker sketch still covers every "
                        "line; trims the scatter-bound share of the device "
                        "step; 0 = full batch)")
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="CHUNKS",
                   help="snapshot (offset, registers) every N chunks")
    p.add_argument("--checkpoint-dir", default=None,
                   help="default: $RA_OUTPUT_DIR/ckpt (see config.py)")
    p.add_argument("--resume", action="store_true",
                   help="resume from --checkpoint-dir if a snapshot exists")
    p.add_argument("--report-every", type=int, default=0, metavar="CHUNKS",
                   help="print throughput to stderr every N chunks")
    p.add_argument("--native-parse", action=argparse.BooleanOptionalAction, default=None,
                   help="use the C++ host parser (default: auto when logs are files)")
    p.add_argument("--packed-input", action="store_true",
                   help="require --logs to be .rawire wire files (see "
                        "`convert`; wire inputs are also auto-detected)")
    p.add_argument("--feed-workers", type=int, default=0, metavar="N",
                   help="parse with N workers over file shards "
                        "(multi-core hosts; implies the native parser; 0/1 = off)")
    p.add_argument("--feed-mode", choices=["process", "thread", "ring"],
                   default="process",
                   help="worker kind for --feed-workers: separate processes "
                        "packing into shared memory, in-process threads "
                        "around the GIL-releasing native parser, or 'ring' — "
                        "one pinned shared-memory ring PER CHIP with a "
                        "partitioned producer pool, each chip's device_put "
                        "fed straight from its own ring (bit-identical "
                        "reports across all three modes)")
    p.add_argument("--coalesce", choices=["off", "on", "auto"], default="off",
                   help="pre-aggregate each batch's duplicate flow tuples "
                        "into (unique row, weight) pairs before the device "
                        "step — shrinks the scatter-bound step, H2D bytes "
                        "and device rows by the traffic's repetition ratio "
                        "with a bit-identical report; 'auto' samples the "
                        "first batches and turns itself off below the "
                        "break-even ratio (single-process runs; for "
                        "--distributed use `convert --coalesce`)")
    p.add_argument("--prefetch-depth", type=int,
                   default=AnalysisConfig.prefetch_depth, metavar="K",
                   help="pipelined ingest: parse/pack/device_put up to K "
                        "batches ahead of the device step on a background "
                        "producer (bit-identical reports; 0 = synchronous "
                        "driver)")
    p.add_argument("--stall-timeout", type=float,
                   default=AnalysisConfig.stall_timeout_sec, metavar="SEC",
                   help="watchdog bound on a pipeline stage making no "
                        "progress before the run aborts with a typed "
                        "StallError (exit code 6) instead of hanging; "
                        "progress resets the window")
    p.add_argument("--fault-plan", default=None, metavar="SPEC",
                   help="ARM deterministic fault injection (testing/chaos "
                        "drills only): 'site@N[,site@N][,seed=S]' fires "
                        "each named site on its Nth hit — the transient "
                        "form site@N:k fires k consecutive hits then "
                        "clears (retry-recovery drills) — or @FILE holding "
                        "the spec; see runtime/faults.py SITES and DESIGN "
                        "§9/§19 for the registered sites")
    p.add_argument("--retry-policy", default="", metavar="SPEC",
                   help="override the typed retry/backoff engine (DESIGN "
                        "§19): 'site=attempts[/base_sec],...,seed=S' "
                        "tunes per-site bounds, 'off' collapses every "
                        "site to a single attempt (A/B measurement); "
                        "empty = the built-in per-site defaults, which "
                        "are always armed")
    p.add_argument("--mesh", choices=["flat", "hybrid"], default="flat",
                   help="device mesh topology: flat = one data axis over "
                        "every device; hybrid = the two-level DCN x ICI "
                        "mesh (an outer between-host axis times an inner "
                        "ICI axis, the create_hybrid_device_mesh idiom) — "
                        "batches shard and registers merge over BOTH "
                        "axes, reports bit-identical to flat (DESIGN §13)")
    p.add_argument("--mesh-dcn", type=int, default=0, metavar="N",
                   help="outer (DCN) extent of --mesh hybrid; 0 = auto "
                        "(process count when multi-host, else 2)")
    p.add_argument("--layout", choices=["flat", "stacked"], default="flat",
                   help="rule-match layout: flat scans all rules per line; stacked "
                        "buckets lines by ACL and vmaps over per-ACL rule slabs "
                        "(faster for many firewalls/ACLs)")
    p.add_argument("--stacked-lane", type=int, default=0, metavar="N",
                   help="per-ACL lane width for --layout=stacked (0 = auto)")
    p.add_argument("--match-impl", choices=["xla", "pallas"],
                   default="xla",
                   help="first-match kernel (bench_suite.py pallas compares them)")
    p.add_argument("--experimental-match-impl", choices=["pallas_fused"],
                   default=None, metavar="IMPL",
                   help="enable an EXPERIMENTAL kernel, overriding "
                        "--match-impl (pallas_fused: match + in-VMEM counts "
                        "in one kernel, measured 0.083x vs xla on TPU — "
                        "logged loudly at run time; bench/research only)")
    p.add_argument("--counts-impl", choices=["scatter", "matmul", "reduce"],
                   default="scatter",
                   help="exact-counts formulation (bench_suite.py stage "
                        "prices them; all bit-identical)")
    p.add_argument("--update-impl", choices=["scatter", "sorted"],
                   default="scatter",
                   help="register-update formulation (DESIGN §15): scatter "
                        "= batch-sized scatter updates; sorted = sort the "
                        "batch's register keys once and segment-reduce "
                        "over the sorted runs (the MapReduce-combiner "
                        "sort half; weight-linear, composes with "
                        "--coalesce).  Reports are bit-identical; "
                        "bench_suite.py stepvariants prices both")
    p.add_argument("--topk-every", type=int, default=1, metavar="N",
                   help="run talker candidate SELECTION every Nth chunk "
                        "only (the talker sketch still absorbs every "
                        "line; heavy hitters recur, so deferred selection "
                        "still surfaces them — trims the candidate-table "
                        "share of the device step; 1 = every chunk)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace here (TensorBoard profile)")
    _add_devprof_flags(p)
    p.add_argument("--trace-out", default=None, metavar="DIR",
                   help="record pipeline spans (parse/pack/H2D/step/"
                        "checkpoint/elastic) + fault-site instants to "
                        "per-process shards in DIR, merged into DIR/"
                        "trace.json at exit — loads in Perfetto / "
                        "chrome://tracing; spawned feeder/elastic workers "
                        "inherit the directory via RA_TRACE_DIR (disarmed "
                        "cost: one None-check per site)")
    p.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="append machine-readable run telemetry (JSON "
                        "lines: lines/s, prefetch queue depth + wait "
                        "times, feeder occupancy, checkpoint bytes/"
                        "latency, recovery events, RSS) to FILE")
    p.add_argument("--metrics-every", type=float, default=10.0, metavar="SEC",
                   help="snapshot cadence of --metrics-out (default 10s)")
    p.add_argument("--distributed", action="store_true",
                   help="join a jax.distributed multi-process job; --logs are "
                        "THIS process's input split (rank 0 prints the report)")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator (default: environment)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--elastic", action="store_true",
                   help="supervise the distributed job elastically: when a "
                        "peer dies the survivors re-form automatically at "
                        "the surviving world size and resume from the "
                        "shared epoch checkpoint.  --logs becomes the FULL "
                        "shard list (identical on every launcher); needs "
                        "--elastic-dir, --checkpoint-every and --json")
    p.add_argument("--elastic-dir", default=None, metavar="DIR",
                   help="shared rendezvous + epoch-checkpoint directory "
                        "for --elastic (must be visible to every launcher)")
    p.add_argument("--max-reforms", type=int, default=2, metavar="N",
                   help="abort after N automatic cluster re-formations "
                        "(the Hadoop max-task-retries analog; default 2)")
    _add_autoscale_flags(p)
    p.add_argument("--static-analysis", action="store_true",
                   help="join static reachability verdicts into the "
                        "report: unused rules split into provably-dead "
                        "(safe to delete) vs traffic-dependent classes, "
                        "and a rule with hits but a dead verdict is a "
                        "typed error (see the `analyze` subcommand; off "
                        "by default — the report is bit-identical without "
                        "it)")
    p.add_argument("--static-witness-budget", type=int, default=4096,
                   metavar="N",
                   help="per-rule witness-grid cap for --static-analysis")
    _add_blackbox_flags(p)
    p.add_argument("--json", action="store_true")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=_cmd_run)

    p = sub.add_parser(
        "doctor",
        help="diagnose a crashed run: postmortem.json (the flight "
             "recorder's merged crash bundle) + exit code -> ranked "
             "causes with next actions — the first-response runbook for "
             "exit codes 3-8",
    )
    p.add_argument("bundle",
                   help="postmortem.json path, or the blackbox directory "
                        "holding one")
    p.add_argument("--exit-code", type=int, default=None, metavar="RC",
                   help="the run's CLI exit code (default: the code "
                        "recorded in the bundle)")
    p.add_argument("--lineage", default=None, metavar="PATH",
                   help="serve dir's lineage.jsonl to join with the "
                        "bundle (default: auto-detected beside the "
                        "bundle); the joined diagnosis names the last "
                        "fully-published window and the first "
                        "missing/incomplete one")
    p.add_argument("--json", action="store_true")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=_cmd_doctor)

    p = sub.add_parser(
        "analyze",
        help="static ruleset analysis (no traffic): per-rule first-match "
             "reachability verdicts — shadowed/redundant/conflict rules "
             "are PROVABLY dead (device-tiled pair relations; union "
             "coverage certified by corner-point witness packets run "
             "through the production match kernel)",
    )
    p.add_argument("--ruleset", required=True,
                   help="packed ruleset path prefix (parse-acls output)")
    p.add_argument("--tile", type=int, default=None, metavar="T",
                   help="pair-tile edge (default 512); the O(R^2)-per-ACL "
                        "grid is walked in [T, T] device tiles")
    p.add_argument("--witness-budget", type=int, default=4096, metavar="N",
                   help="per-rule cap on witness-grid enumeration; a rule "
                        "whose corner grid exceeds it stays "
                        "partially-masked/uncertified instead of dead "
                        "(dead verdicts always carry a complete proof)")
    p.add_argument("--fault-plan", default=None, metavar="SPEC",
                   help="chaos drills (adds the analyze.tile site); see "
                        "`run --fault-plan`")
    p.add_argument("--json", action="store_true")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=_cmd_analyze)

    p = sub.add_parser(
        "lint",
        help="ralint: static program-invariant verification — traces "
             "every shipping step program to a closed jaxpr (abstract "
             "eval; no device, no compile) and proves weight-linearity, "
             "scatter safety, ra.* scope coverage, and merge-law "
             "conformance; audits repo registries (fault sites, CLI "
             "flags vs docs, volatile totals keys)",
    )
    p.add_argument("--fast", action="store_true",
                   help="lint the representative program subset instead "
                        "of the full impl grid (the tier-1 test budget)")
    p.add_argument("--skip-registry", action="store_true",
                   help="skip the repo registry auditor (jaxpr checks only)")
    p.add_argument("--repo-root", default=None, metavar="DIR",
                   help="repo root for the registry auditor (default: "
                        "the installed package's parent)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=_cmd_lint)

    p = sub.add_parser(
        "serve",
        help="always-on service mode: live syslog listeners feed "
             "time-windowed registers; windowed/cumulative reports "
             "publish on every rotation to --serve-dir and a loopback "
             "JSON endpoint; SIGHUP (or a watched ruleset-file change) "
             "hot-reloads the rule tensor with counter migration",
    )
    p.add_argument("--ruleset", default=None, help="packed ruleset path prefix "
                   "(re-read on reload); exactly one of --ruleset/--tenants")
    p.add_argument("--tenants", default=None, metavar="MANIFEST",
                   help="multi-tenant mode (runtime/tenantserve.py): a JSON "
                        "manifest of tenants ({'tenants': [{'name', "
                        "'ruleset', 'listen': [...], 'hosts': [...], "
                        "'default': bool}]}) hosts MANY rulesets on one "
                        "mesh — per-tenant windows/reports under "
                        "SERVE_DIR/t/<name>/, per-tenant HTTP routes "
                        "(/tenants, /t/<name>/report...), tenant-labeled "
                        "/metrics, and per-tenant hot reload that never "
                        "pauses other tenants; lines route by @tenant "
                        "tag > per-tenant listener > syslog hostname > "
                        "manifest default")
    p.add_argument("--listen", action="append", default=[], metavar="SPEC",
                   help="ingress (repeatable): udp:HOST:PORT, "
                        "tcp:HOST:PORT (newline-framed), or tail:PATH "
                        "(rotating-file tailer)")
    p.add_argument("--window", required=True, metavar="W",
                   help="rotation cadence: a duration (900s, 15m, 24h) or "
                        "lines:N (deterministic line-count windows)")
    p.add_argument("--ring", type=int, default=8, metavar="N",
                   help="window epochs retained for merged views (default 8)")
    p.add_argument("--view", action="append", type=int, default=[],
                   metavar="K",
                   help="also publish a merged view of the last K windows "
                        "at every rotation (repeatable; e.g. --view 24 "
                        "--view 168 for 24h/7d at a 1h window)")
    p.add_argument("--serve-dir", required=True,
                   help="reports/endpoint/checkpoint directory")
    p.add_argument("--http", default="127.0.0.1:0", metavar="HOST:PORT",
                   help="JSON endpoint bind (port 0 = ephemeral, recorded "
                        "in serve-dir/endpoint.json; 'off' disables). "
                        "Paths: /report /report/cumulative "
                        "/report/window/<id> /report/merged/<k> /diff "
                        "/health /metrics")
    p.add_argument("--queue-lines", type=int, default=1 << 16, metavar="N",
                   help="listener queue capacity; lines past it DROP with "
                        "an explicit count and the window is published "
                        "with a WindowIncomplete marker (default 65536)")
    p.add_argument("--checkpoint-every-windows", type=int, default=1,
                   metavar="N",
                   help="checkpoint the window ring every N rotations "
                        "(0 = never; a restarted serve --resume keeps its "
                        "history)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="default: SERVE_DIR/ckpt")
    p.add_argument("--resume", action="store_true",
                   help="restore the window ring from --checkpoint-dir")
    p.add_argument("--reload-watch", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="poll the ruleset files and hot-reload on change "
                        "(SIGHUP reloads regardless)")
    p.add_argument("--reload-poll", type=float, default=2.0, metavar="SEC")
    p.add_argument("--max-windows", type=int, default=0, metavar="N",
                   help="stop after N rotations (0 = run forever)")
    p.add_argument("--stop-after", type=float, default=0.0, metavar="SEC",
                   help="soft wall-clock deadline (0 = none)")
    p.add_argument("--batch-size", type=int, default=1 << 16)
    p.add_argument("--cms-width", type=int, default=1 << 14)
    p.add_argument("--cms-depth", type=int, default=4)
    p.add_argument("--hll-p", type=int, default=8)
    p.add_argument("--register-budget-mb", type=int, default=4096, metavar="MB")
    p.add_argument("--topk", type=int, default=10)
    p.add_argument("--stall-timeout", type=float,
                   default=AnalysisConfig.stall_timeout_sec, metavar="SEC")
    p.add_argument("--update-impl", choices=["scatter", "sorted"],
                   default="scatter",
                   help="register-update formulation (see `run "
                        "--update-impl`; bit-identical windows)")
    p.add_argument("--topk-every", type=int, default=1, metavar="N",
                   help="defer talker candidate selection to every Nth "
                        "chunk (see `run --topk-every`)")
    p.add_argument("--static-analysis", action="store_true",
                   help="run the static ruleset analyzer at start and on "
                        "every hot reload (unchanged ACLs reuse their "
                        "verdicts): /report/static publishes the verdict "
                        "table, every window report's unused rules carry "
                        "evidence classes (provably-dead vs "
                        "traffic-dependent), and /metrics gains "
                        "static_analysis_age_sec / "
                        "static_analysis_duration_sec")
    p.add_argument("--static-witness-budget", type=int, default=4096,
                   metavar="N",
                   help="per-rule witness-grid cap for the serve analyzer "
                        "(see `analyze --witness-budget`)")
    p.add_argument("--wal", action="store_true",
                   help="durable ingest write-ahead log (DESIGN §19): "
                        "every consumed line spools to segmented, CRC'd "
                        "on-disk records BEFORE window accounting, so "
                        "serve --resume after a hard kill replays the "
                        "interrupted window bit-identical over its "
                        "delivered lines; eviction/corruption losses are "
                        "exactly counted, never silent")
    p.add_argument("--wal-dir", default="",
                   help="WAL directory (default: SERVE_DIR/wal)")
    p.add_argument("--wal-segment-kb", type=int, default=1024, metavar="KB",
                   help="bytes per WAL segment before rolling (default "
                        "1024 KiB)")
    p.add_argument("--wal-budget-mb", type=int, default=64, metavar="MB",
                   help="total on-disk WAL budget; past it the oldest "
                        "segment evicts with its records counted as "
                        "explicit drops at the next resume (default 64)")
    p.add_argument("--lineage", choices=["on", "off"], default="on",
                   help="window provenance plane (DESIGN §24, default "
                        "on): every published window carries a sealed "
                        "totals.lineage record — contributing hosts with "
                        "their delivered WAL ranges, drop/quarantine "
                        "counts, supervisor term, publication path "
                        "(live/replay/backlog_heal), reload generation, "
                        "CRC — appended durably to SERVE_DIR/"
                        "lineage.jsonl and served at /lineage; 'off' "
                        "drops the plane for benchmarking the overhead")
    p.add_argument("--slo", default="", metavar="SPEC",
                   help="SLO burn-rate alerting over published windows "
                        "(Google SRE fast/slow pairs), e.g. "
                        "'p99_publish_ms<=500,drop_rate<=0.001': each "
                        "objective tracks fast(3)/slow(12)-window burn "
                        "rates; crossing 2x fast AND 1x slow emits a "
                        "typed slo.breach event (obs instant + metrics "
                        "JSONL + flight recorder) and slo.recovered "
                        "after 3 clean windows.  Metrics: "
                        "p50/p90/p99_publish_ms, drop_rate, "
                        "incomplete_rate, degraded_subsystems")
    p.add_argument("--epoch-store", default="", metavar="DIR",
                   help="durable epoch store + segment-tree summaries "
                        "(DESIGN §25): every rotated window spills to "
                        "CRC'd segment chains under DIR and compaction "
                        "maintains power-of-two merged nodes, so "
                        "/report/range?from=&to= renders any [t0,t1] "
                        "report from <= 2*log2(n) stored aggregates — "
                        "bit-identical to folding the raw epochs, no "
                        "replay — and /report/last-hit serves each "
                        "rule's last-hit window + wall time (the quiet "
                        "horizon safe_to_delete verdicts cite).  Bounds "
                        "range by id or unix seconds; a range the store "
                        "cannot fully cover answers a typed "
                        "range_incomplete, never silent zeros")
    p.add_argument("--epoch-store-budget-mb", type=int, default=512,
                   metavar="MB",
                   help="total on-disk epoch-store budget; past it the "
                        "oldest RAW-epoch segment evicts first (coarse "
                        "summary nodes still answer aligned queries "
                        "over the evicted span) (default 512)")
    p.add_argument("--trend-threshold", type=float, default=4.0,
                   metavar="X",
                   help="per-rule traffic trend events in diff.json: a "
                        "rule whose per-line hit rate grows by more "
                        "than Xx between consecutive windows emits "
                        "rule_burst, shrinking by Xx emits rule_quiet, "
                        "with sqrt(X) hysteresis so steady load near "
                        "the boundary never storms (0 disables; "
                        "default 4.0)")
    p.add_argument("--mesh", choices=["flat", "hybrid"], default="flat",
                   help="device mesh topology (parallel/mesh.py); "
                        "--distributed requires 'hybrid' (the host tier "
                        "IS the outer dcn axis, DESIGN §22)")
    p.add_argument("--distributed", action="store_true",
                   help="multi-host serve (runtime/distserve.py, DESIGN "
                        "§22): each host runs its own listener tier + "
                        "WAL + local mesh ingesting into host-local "
                        "registers; window epochs merge across hosts at "
                        "rank 0 under the register merge laws, so the "
                        "published report is bit-identical to a single-"
                        "host replay of the union of all hosts' "
                        "delivered lines.  Rank 0 owns publication, "
                        "HTTP, and the merged-ring checkpoint; listener "
                        "ports offset by host rank")
    p.add_argument("--dist-hosts", type=int, default=2, metavar="N",
                   help="ingest hosts to launch (default 2)")
    p.add_argument("--dist-min-hosts", type=int, default=1, metavar="N",
                   help="host-tier autoscale ladder floor (default 1)")
    p.add_argument("--dist-max-hosts", type=int, default=0, metavar="N",
                   help="host-tier ladder ceiling (0 = --dist-hosts). "
                        "Part of the checkpoint resume identity: any "
                        "live host count resumes any other under the "
                        "SAME ceiling")
    p.add_argument("--dist-workers", choices=["process", "thread"],
                   default="process",
                   help="host worker isolation (process = one OS process "
                        "per host, the production mode; thread = "
                        "in-process, the deterministic test mode)")
    p.add_argument("--dist-merge-bind", default="127.0.0.1:0",
                   metavar="HOST:PORT",
                   help="rank-0 merge-plane bind for process workers "
                        "(port 0 = ephemeral, recorded in endpoint.json)")
    p.add_argument("--dist-merge-timeout", type=float, default=120.0,
                   metavar="SEC",
                   help="max wait for a live host's epoch past a "
                        "window's first arrival before publishing "
                        "without it (named host_missing; default 120)")
    p.add_argument("--dist-respawn", action="store_true",
                   help="respawn a dead host at the merge frontier; its "
                        "WAL replays the lost tail on rejoin")
    p.add_argument("--dist-lease-ttl", type=float, default=2.0,
                   metavar="SEC",
                   help="supervisor-lease TTL (DESIGN §23): a holder "
                        "that cannot renew this long self-fences (stops "
                        "publishing, exits typed code 8); a successor "
                        "steals only after 1.5x, so takeover completes "
                        "within ~2x TTL with no split brain.  0 "
                        "disables the lease/failover plane (default 2)")
    p.add_argument("--dist-spool-dir", default="", metavar="DIR",
                   help="durable per-host epoch spool + lease root "
                        "(default: under --serve-dir).  Point at shared "
                        "storage so a successor elsewhere can replay "
                        "every host's spooled window epochs")
    p.add_argument("--dist-spool-budget-mb", type=int, default=64,
                   metavar="MB",
                   help="per-host epoch-spool disk budget; oldest "
                        "segments evict first, counted never silent "
                        "(0 disables spooling; default 64)")
    _add_autoscale_flags(p)
    _add_blackbox_flags(p)
    p.add_argument("--fault-plan", default=None, metavar="SPEC",
                   help="chaos drills: see `run --fault-plan` (adds the "
                        "listener.drop/listener.stall/reload.midbatch, "
                        "listener.bind.fail/listener.accept.fail/"
                        "serve.publish.fail/metrics.snapshot.fail, and "
                        "autoscale.decide/autoscale.spawn sites)")
    p.add_argument("--retry-policy", default="", metavar="SPEC",
                   help="retry/backoff overrides: see `run --retry-policy`")
    _add_devprof_flags(p)
    p.add_argument("--trace-out", default=None, metavar="DIR",
                   help="record listener/rotation/reload spans (see "
                        "`run --trace-out`)")
    p.add_argument("--metrics-out", default=None, metavar="FILE",
                   help="append queue/drop gauges + window events as JSON "
                        "lines")
    p.add_argument("--metrics-every", type=float, default=10.0, metavar="SEC")
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser(
        "convert",
        help="pre-tokenize text syslog into a .rawire wire file "
             "(16 B/line; `run` feeds it to the device with no host parse)",
    )
    p.add_argument("--ruleset", required=True, help="packed ruleset path prefix")
    p.add_argument("--logs", nargs="+", required=True, help="text syslog file(s)")
    p.add_argument("--out", required=True, help="output .rawire path")
    p.add_argument("--native-parse", action=argparse.BooleanOptionalAction, default=None,
                   help="use the C++ parser for the one-time conversion (default: auto)")
    p.add_argument("--block-rows", type=int, default=1 << 16, metavar="N",
                   help="rows per payload block; match the run --batch-size "
                        "for the zero-copy mmap read path (default 65536)")
    p.add_argument("--feed-workers", type=int, default=0, metavar="N",
                   help="parse with N worker processes (multi-core one-time "
                        "conversion; output is byte-identical; 0/1 = off)")
    p.add_argument("--coalesce", action="store_true",
                   help="write the weighted v3 format: per-batch duplicate "
                        "flow tuples store once with a repetition count "
                        "(20 B/row + weights; bit-identical reports, file "
                        "and every later device step shrink by the "
                        "corpus's compaction ratio)")
    p.add_argument("--workers", type=int, default=0, metavar="N",
                   help="convert FLEET: shard the corpus by exact-raw-line "
                        "descriptors across N worker processes, each "
                        "writing one pre-coalesced RAWIREv3 shard; --out "
                        "becomes a merge manifest `run` consumes as one "
                        "corpus (bit-identical for any N; implies the "
                        "weighted format; 0 = classic single-file convert)")
    p.set_defaults(fn=_cmd_convert)

    p = sub.add_parser(
        "wire-info",
        help="inspect .rawire wire-file headers (row/line counts, "
             "integrity; --ruleset validates the fingerprint)",
    )
    p.add_argument("files", nargs="+", help=".rawire file(s)")
    p.add_argument("--ruleset", default=None,
                   help="packed ruleset prefix to validate the fingerprint against")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_wire_info)

    p = sub.add_parser(
        "diff-reports",
        help="compare two `run --json` reports: stable-unused deletion "
             "candidates, newly used/unused rules, top hit movers",
    )
    p.add_argument("old", help="earlier report (run --json output)")
    p.add_argument("new", help="later report")
    p.add_argument("--top", type=int, default=10, help="hit movers to show")
    p.add_argument("--expect-window", default=None, metavar="W",
                   help="require BOTH reports to be serve-mode window "
                        "reports of exactly this window (lines:N or a "
                        "duration like 24h); a mismatch is a typed "
                        "refusal, not a misleading diff")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=_cmd_diff_reports)

    p = sub.add_parser("synth", help="generate synthetic config + syslog")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--acls", type=int, default=4)
    p.add_argument("--rules", type=int, default=32)
    p.add_argument("--lines", type=int, default=10000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--hostname", default="fw1")
    p.add_argument("--v6-fraction", type=float, default=0.0,
                   help="fraction of ACEs (and log lines) spelled IPv6 — "
                        "generates a unified v4+v6 config and mixed corpus")
    p.add_argument("--flows", type=int, default=0, metavar="M",
                   help="draw lines from a pool of M distinct flows with "
                        "Zipf(--skew) repetition (the coalescing tier's "
                        "feedstock; 0 = independent lines as before)")
    p.add_argument("--skew", type=float, default=1.0, metavar="S",
                   help="Zipf exponent for --flows (0 = uniform; larger "
                        "concentrates traffic on head flows; default 1.0)")
    p.set_defaults(fn=_cmd_synth)
    return ap


def _finalize_obs() -> None:
    """Stop the metrics thread + merge trace shards, typed aborts included.

    Runs from ``main``'s finally so a run that dies with an
    AnalysisError still leaves ONE merged timeline — a disarmed run
    exits through two None-checks.
    """
    from .runtime import devprof, obs

    try:
        cap = devprof.active_capture()
        if cap is not None and getattr(cap, "json_path", None):
            print(
                f"devprof: {cap.json_path} (per-stage attribution; diff "
                "two captures with tools/trace_diff.py)",
                file=sys.stderr,
            )
    except Exception as e:
        print(f"warning: devprof summary hint failed: {e}", file=sys.stderr)
    try:
        merged = obs.shutdown()
    except Exception as e:  # a broken merge must not mask the run's rc
        print(f"warning: trace merge failed: {e}", file=sys.stderr)
        merged = None
    finally:
        # AFTER obs.shutdown: the metrics plane's final snapshot must
        # still see the devprof/device_mem samplers; this stops any
        # dangling profiler window (typed-abort path) without parsing —
        # never a hang or a half-written devprof.json
        try:
            devprof.shutdown()
        except Exception as e:
            print(f"warning: devprof shutdown failed: {e}", file=sys.stderr)
    if merged:
        print(
            f"trace: {merged} (open in Perfetto or chrome://tracing; "
            "summarize with tools/trace_summary.py)",
            file=sys.stderr,
        )


def _finalize_blackbox() -> None:
    """Dump + merge the flight recorder on abort; prune on a clean exit.

    Runs from ``main``'s finally: by now the error handlers have noted
    any typed abort (and an unhandled exception is still in flight on
    ``sys.exc_info``), so an aborted run leaves ONE ``postmortem.json``
    and a clean run leaves nothing (DESIGN §20).
    """
    from .runtime import flightrec

    try:
        pm = flightrec.finalize()
    except Exception as e:  # forensics must never mask the run's rc
        print(f"warning: postmortem merge failed: {e}", file=sys.stderr)
        return
    if pm:
        print(
            f"postmortem: {pm} (diagnose with `ruleset-analyze doctor "
            f"{pm}`)",
            file=sys.stderr,
        )


def main(argv: list[str] | None = None) -> int:
    args = make_parser().parse_args(argv)
    from .runtime import flightrec

    try:
        return args.fn(args)
    except aclparse.AclParseError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except errors.AnalysisError as e:
        # failure-class exit codes (errors.exit_code_for, README "Exit
        # codes"): supervisors/operators branch on corrupt checkpoint vs
        # resume mismatch vs feed failure vs stall vs reform budget
        print(f"error: {e}", file=sys.stderr)
        rc = errors.exit_code_for(e)
        flightrec.note_abort(e, rc)
        return rc
    except ValueError as e:
        # User-reachable library validation (corrupt packed-ruleset files,
        # bad distributed divisibility, malformed wire arrays) surfaces as
        # ValueError; a CLI should report it cleanly, not traceback.  The
        # trade-off (a genuine bug raising ValueError also loses its
        # traceback) is accepted for the operator-facing tool; run with
        # RA_DEBUG=1 to re-raise.
        import os

        if os.environ.get("RA_DEBUG"):
            raise
        print(f"error: {e}", file=sys.stderr)
        return 2
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # stdout consumer (head, less) closed early — normal, not an error
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    finally:
        _finalize_obs()
        # AFTER obs: a dump's sampler snapshot may read gauges the
        # metrics close would otherwise race; an unhandled exception is
        # still on sys.exc_info here, so finalize sees it
        _finalize_blackbox()


if __name__ == "__main__":
    raise SystemExit(main())


def main_entry() -> None:
    """console_scripts entry point (pyproject.toml: ``ruleset-analyze``)."""
    raise SystemExit(main())
