"""The ``ra.*`` named-scope stage taxonomy (DESIGN §14) — ONE source.

Every register-update stage in ``ops/`` and the dispatch seams in
``parallel/step.py`` trace under ``jax.named_scope`` labels from this
taxonomy.  Scopes ride HLO op *metadata* (``op_name``) through XLA's
optimizer, so profiler fusions — even renumbered ones — carry the
stages they fused; they also land on every jaxpr equation's
``source_info.name_stack``, which is how the static lint plane
(``verify/``) proves scope coverage without a device.

Three consumers import this module so the taxonomy can never drift
between them:

- ``runtime/devprof.py`` — in-process capture windows classify profiled
  events by these stages;
- ``tools/trace_attrib.py`` — offline trace attribution flags ``ra.*``
  tokens that are NOT in the taxonomy (a scope someone added without
  registering it here);
- ``ruleset_analysis_tpu/verify`` — the jaxpr linter requires every
  register-update primitive to attribute to exactly one member stage
  (DESIGN §18).

Classification accepts any ``ra.<word>`` token syntactically — but an
unregistered token is a lint finding, so adding a stage means adding it
HERE (with its one-line meaning) and nowhere else.

The stages the step programs emit today:

   ra.unpack  wire bit-unpack + the coalesce weight plane (batch_cols)
   ra.match   v4 first-match kernel (flat + stacked + pallas epilogues)
   ra.match6  v6 lexicographic limb match + source fold
   ra.counts  exact per-key counts (scatter/matmul/reduce impls + add64)
   ra.cms     per-rule count-min scatter
   ra.hll     per-key HLL scatter-max
   ra.talk    talker (acl, src) sketch update
   ra.topk    chunk-local candidate table + top_k selection
   ra.sort    register-key sorts feeding the segment-reduce updates
              (update_impl=sorted, ops/sorted_update.py — DESIGN §15)
   ra.overlap static-analysis pairwise rule-relation tiles (ISSUE 12)
   ra.merge   cross-device psum/pmax/all_gather merges
"""

from __future__ import annotations

import re

STAGES = (
    "ra.unpack",
    "ra.match",
    "ra.match6",
    "ra.counts",
    "ra.cms",
    "ra.hll",
    "ra.talk",
    "ra.topk",
    "ra.sort",
    "ra.merge",
    "ra.overlap",
)

#: Syntactic shape of a stage token inside an HLO op_name path or a
#: jaxpr name stack.  Matching is deliberately broader than
#: :data:`STAGES` membership: classifiers accept any token (so captures
#: from newer code still attribute), while the lint plane additionally
#: enforces membership (so new tokens must be registered above).
SCOPE_RE = re.compile(r"ra\.[a-z0-9_]+")


def scope_of(op_name: str | None) -> str | None:
    """Outermost ``ra.*`` scope token of an HLO ``op_name`` path or a
    jaxpr ``name_stack`` string.

    Outermost wins so a wrapping stage owns its helpers: the talker
    plane's ``ra.talk/ra.cms/...`` classifies as ``ra.talk`` even though
    the inner scatter is the shared CMS kernel.
    """
    m = SCOPE_RE.search(op_name or "")
    return m.group(0) if m else None
