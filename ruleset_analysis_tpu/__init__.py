"""ruleset_analysis_tpu — a TPU-native firewall ruleset-analysis framework.

A from-scratch rebuild of the capabilities of ``arnesund/ruleset-analysis``
(Cisco ASA access-list usage analysis over syslog at scale), re-designed
TPU-first:

- the host-side ruleset parser (the ``getaccesslists.py`` analog, see
  SURVEY.md L1) emits a packed, device-resident *rule tensor*;
- the per-log-line first-match scan (the ``mapper.py`` hot loop, SURVEY.md
  L3) becomes a vmapped branch-free predicate over packed 5-tuple batches,
  compiled by XLA for the TPU vector unit;
- the exact streaming reduction (``reducer.py``, SURVEY.md L4) becomes
  on-device exact bincounts plus mergeable sketches (count-min sketch,
  HyperLogLog, heavy-hitter candidates) merged across chips with XLA
  collectives (``psum``/``pmax``) over ICI instead of a Hadoop shuffle.

Subpackages
-----------
hostside  : pure-Python host layer — ASA config parsing, object-group
            expansion, syslog parsing, the exact oracle, synthetic data.
ops       : JAX device ops — first-match kernel, hashing, CMS, HLL, top-K.
models    : the flagship analysis pipeline (state + jitted step function).
parallel  : mesh construction and shard_map'd data-parallel step.
runtime   : streaming driver, checkpoint/resume, reporting, metrics.
"""

__version__ = "0.1.0"
