"""ralint — the static program-invariant lint plane (DESIGN §18).

The repo's load-bearing register invariants — weight-linearity under
coalescing (DESIGN §11), scatter OOB/sorted contracts (§15), ``ra.*``
attribution completeness (§14), and the ``_merge_tail`` merge laws —
were defended by runtime bit-identity tests and hand-maintained refusal
lists.  Every new impl axis (``counts_impl x match_impl x update_impl x
coalesce x topk_every``) multiplies the combinations those hand lists
must cover.  This package derives the invariants FROM THE TRACED
PROGRAMS instead, once, statically:

- :mod:`.grid` traces every shipping step program (the full impl grid,
  v4+v6, flat+stacked) to a closed jaxpr by abstract eval — no device
  data, no XLA compile;
- :mod:`.jaxpr_lint` walks each jaxpr and verifies weight-linearity
  (taint walk from the weight plane to every register sink), scatter
  safety (``mode=drop``; ``indices_are_sorted`` only downstream of a
  sort), scope coverage (every register-update primitive attributes to
  exactly one registered ``ra.*`` stage), and merge-law conformance
  (every register output reaches the host through its law's collective);
- :mod:`.registry` audits the repo-level registries that the jaxprs
  cannot see: fault sites <-> armed call sites <-> test coverage, CLI
  flags <-> README <-> PARITY, and the VOLATILE totals keys <-> actual
  report totals producers;
- :mod:`.report` assembles everything into one report (text or JSON)
  for the ``lint`` CLI subcommand and ``tools/ralint.py``.

An invariant the walker cannot prove is an ``unprovable`` verdict — a
typed refusal with today's exact behavior, never a silent pass.
"""

from .grid import (  # noqa: F401
    LINT_GEOMETRY,
    ProgramSpec,
    fast_grid,
    shipping_grid,
    trace_program,
)
from .jaxpr_lint import Finding, ProgramLint, lint_program  # noqa: F401
from .registry import audit_epochstore, audit_registry  # noqa: F401
from .report import LintReport, render_text, run_lint  # noqa: F401
