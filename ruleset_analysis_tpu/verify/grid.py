"""Shipping step-program grid + abstract tracing (no device, no compile).

Every lintable program is one of the post-unpack shard-step cores
(``parallel/step.py::CORES`` — the SAME functions the shipping steps
call after ``batch_cols``), wrapped in a one-device ``shard_map`` so the
collective merge seams (``psum``/``pmax``/``all_gather``) trace as
explicit primitives, and traced with ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` arguments: abstract eval only — no device buffer
is created and no XLA compile runs, which is what keeps the whole grid
under the ``make lint`` budget on a 1-core host.

The weight plane enters the wrapper as its OWN argument (the cores were
split from the unpack for exactly this), so the taint walk in
:mod:`.jaxpr_lint` can seed taint at a top-level jaxpr invar instead of
chasing a slice of the packed batch.

Grid membership is derived from :class:`~..config.AnalysisConfig`
validation itself: a combination the config refuses at construction
time is not a shipping program and is skipped — so when a future PR
adds or retires an impl axis, the grid follows automatically.
"""

from __future__ import annotations

import dataclasses
import functools

#: Small abstract geometry for lint traces.  Verdicts are structural
#: (which primitives, which operands, which scopes), not shape-
#: dependent, so a small geometry proves the same program shape the
#: production sizes run — while keeping ~100 traces cheap.
LINT_GEOMETRY = dict(
    batch=256,  # lines per shard
    rules=128,  # v4 ACE rows (== one RULE_BLOCK / RULE_TILE multiple)
    rules6=128,  # v6 ACE rows
    n_keys=16,  # count-key universe
    n_acls=4,
    cms_depth=2,
    cms_width=256,
    hll_m=16,
    topk_k=8,
    groups=2,  # stacked: ACL groups
    lane=128,  # stacked: per-group lane width
    tenants=4,  # tenant: bucket stack depth (leading register axis)
)


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One shipping step-program coordinate in the impl grid."""

    kind: str  # {"flat", "stacked", "v6", "tenant"}
    match_impl: str = "xla"
    counts_impl: str = "scatter"
    update_impl: str = "scatter"
    topk_every: int = 1
    topk_sample_shift: int = 0
    exact_counts: bool = True

    @property
    def name(self) -> str:
        parts = [self.kind, self.match_impl, self.counts_impl, self.update_impl]
        if self.topk_every != 1:
            parts.append(f"te{self.topk_every}")
        if self.topk_sample_shift:
            parts.append(f"ss{self.topk_sample_shift}")
        if not self.exact_counts:
            parts.append("noexact")
        return "step." + ",".join(parts)

    def config_kwargs(self) -> dict:
        """AnalysisConfig kwargs naming this combination (validation)."""
        from ..config import SketchConfig

        return dict(
            match_impl=self.match_impl if self.kind == "flat" else "xla",
            counts_impl=self.counts_impl,
            update_impl=self.update_impl,
            layout="stacked" if self.kind == "stacked" else "flat",
            sketch=SketchConfig(
                topk_every=self.topk_every,
                topk_sample_shift=self.topk_sample_shift,
                cms_depth=LINT_GEOMETRY["cms_depth"],
                cms_width=LINT_GEOMETRY["cms_width"],
                talk_cms_depth=LINT_GEOMETRY["cms_depth"],
            ),
            exact_counts=self.exact_counts,
        )

    def is_shipping(self) -> bool:
        """True iff AnalysisConfig accepts this combination."""
        from ..config import AnalysisConfig

        try:
            AnalysisConfig(**self.config_kwargs())
        except ValueError:
            return False
        return True


#: (topk_every, topk_sample_shift) variants traced per impl combination:
#: the plain path, the deferred-selection cond path, and the sampled-
#: selection path — each changes which candidate-table program traces.
_TOPK_VARIANTS = ((1, 0), (4, 0), (1, 2))


def shipping_grid() -> list[ProgramSpec]:
    """Every shipping step program: the full impl grid, all kinds."""
    specs: list[ProgramSpec] = []
    for kind in ("flat", "stacked", "v6", "tenant"):
        match_impls = (
            ("xla", "pallas", "pallas_fused") if kind == "flat" else ("xla",)
        )
        for mi in match_impls:
            for ci in ("scatter", "matmul", "reduce"):
                for ui in ("scatter", "sorted"):
                    for te, ss in _TOPK_VARIANTS:
                        s = ProgramSpec(
                            kind=kind, match_impl=mi, counts_impl=ci,
                            update_impl=ui, topk_every=te,
                            topk_sample_shift=ss,
                        )
                        if s.is_shipping():
                            specs.append(s)
    # the no-exact-counts mode drops the counts registers' merge seam by
    # design — one representative program pins the linter's exemption
    specs.append(ProgramSpec(kind="flat", exact_counts=False))
    return specs


def fast_grid() -> list[ProgramSpec]:
    """Tier-1 subset: every verdict class and every check dimension at
    least once (one program per distinct structure family)."""
    return [
        ProgramSpec(kind="flat"),
        ProgramSpec(kind="flat", update_impl="sorted", topk_every=4),
        ProgramSpec(kind="flat", counts_impl="matmul"),
        ProgramSpec(kind="flat", counts_impl="reduce", update_impl="sorted"),
        ProgramSpec(kind="flat", match_impl="pallas"),
        ProgramSpec(kind="flat", match_impl="pallas_fused"),
        ProgramSpec(kind="stacked", topk_sample_shift=2),
        ProgramSpec(kind="v6", update_impl="sorted"),
        ProgramSpec(kind="flat", exact_counts=False),
        # tenant-sliced register planes: dynamic slice/update around the
        # flat core — one program pins the wrapper's lint verdict
        ProgramSpec(kind="tenant"),
    ]


@dataclasses.dataclass(frozen=True)
class TracedProgram:
    """One traced program: the closed jaxpr + lint bookkeeping."""

    spec: ProgramSpec
    closed_jaxpr: object  # jax.core.ClosedJaxpr
    weight_invar_index: int  # flat index of the weight plane input
    output_names: tuple[str, ...]  # flatten order of (state, chunk_out)


#: Flatten order of the step outputs (AnalysisState, ChunkOut) — the
#: NamedTuple field order, pinned here so the merge-law table in
#: jaxpr_lint addresses outputs by name.
OUTPUT_NAMES = (
    "counts_lo", "counts_hi", "cms", "hll", "talk_cms",
    "cand_acl", "cand_src", "cand_est",
)

_V4_FIELDS = ("acl", "proto", "src", "sport", "dst", "dport")


def _sds(shape, dtype=None):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), dtype or jnp.uint32)


def _abstract_args(spec: ProgramSpec):
    """(state, ruleset, cols, valid[, tid], salt) ShapeDtypeStructs for
    `spec` — the weight plane (``valid``) is ALWAYS args[3], which is
    what trace_program's marker flatten relies on."""
    from ..hostside.pack import RULE6_COLS, RULE_COLS
    from ..models.pipeline import (
        AnalysisState, DeviceRuleset, DeviceRuleset6, DeviceRulesetStacked,
        DeviceRulesetTenant,
    )

    g = LINT_GEOMETRY
    state = AnalysisState(
        counts_lo=_sds((g["n_keys"],)),
        counts_hi=_sds((g["n_keys"],)),
        cms=_sds((g["cms_depth"], g["cms_width"])),
        hll=_sds((g["n_keys"], g["hll_m"])),
        talk_cms=_sds((g["cms_depth"], g["cms_width"])),
    )
    salt = _sds(())
    if spec.kind == "tenant":
        import jax.numpy as jnp

        t = g["tenants"]
        state = AnalysisState(
            counts_lo=_sds((t, g["n_keys"])),
            counts_hi=_sds((t, g["n_keys"])),
            cms=_sds((t, g["cms_depth"], g["cms_width"])),
            hll=_sds((t, g["n_keys"], g["hll_m"])),
            talk_cms=_sds((t, g["cms_depth"], g["cms_width"])),
        )
        ruleset = DeviceRulesetTenant(
            rules_t=_sds((t, g["rules"], RULE_COLS)),
            deny_key_t=_sds((t, g["n_acls"])),
        )
        cols = {k: _sds((g["batch"],)) for k in _V4_FIELDS}
        valid = _sds((g["batch"],))
        tid = _sds((), jnp.int32)
        return state, ruleset, cols, valid, tid, salt
    if spec.kind == "flat":
        rules_fm = (
            _sds((RULE_COLS, g["rules"]))
            if spec.match_impl in ("pallas", "pallas_fused")
            else None
        )
        ruleset = DeviceRuleset(
            rules=_sds((g["rules"], RULE_COLS)),
            deny_key=_sds((g["n_acls"],)),
            rules_fm=rules_fm,
        )
        cols = {k: _sds((g["batch"],)) for k in _V4_FIELDS}
        valid = _sds((g["batch"],))
    elif spec.kind == "stacked":
        ruleset = DeviceRulesetStacked(
            rules3d=_sds((g["groups"], g["rules"], RULE_COLS)),
            deny_key=_sds((g["n_acls"],)),
        )
        cols = {k: _sds((g["groups"], g["lane"])) for k in _V4_FIELDS}
        valid = _sds((g["groups"], g["lane"]))
    elif spec.kind == "v6":
        ruleset = DeviceRuleset6(
            rules6=_sds((g["rules6"], RULE6_COLS)),
            deny_key=_sds((g["n_acls"],)),
        )
        cols = {k: _sds((g["batch"],)) for k in ("acl", "proto", "sport", "dport")}
        for i in range(4):
            cols[f"src{i}"] = _sds((g["batch"],))
            cols[f"dst{i}"] = _sds((g["batch"],))
        valid = _sds((g["batch"],))
    else:
        raise ValueError(f"unknown program kind {spec.kind!r}")
    return state, ruleset, cols, valid, salt


def _core_kwargs(spec: ProgramSpec) -> dict:
    g = LINT_GEOMETRY
    kw = dict(
        axis="data",
        n_keys=g["n_keys"],
        topk_k=g["topk_k"],
        exact_counts=spec.exact_counts,
        rule_block=g["rules"],
        topk_sample_shift=spec.topk_sample_shift,
        counts_impl=spec.counts_impl,
        update_impl=spec.update_impl,
        topk_every=spec.topk_every,
    )
    if spec.kind == "flat":
        kw["match_impl"] = spec.match_impl
    # the tenant core runs _core_flat on the sliced plane with the XLA
    # match fixed (make_tenant_step never specializes); no extra kwarg
    return kw


@dataclasses.dataclass(frozen=True)
class FixtureSpec:
    """Spec stand-in for hand-built mini-programs (negative fixtures)."""

    name: str
    exact_counts: bool = True


def trace_fixture(
    fn,
    args,
    weight_arg: int,
    output_names: tuple[str, ...],
    name: str = "fixture",
) -> TracedProgram:
    """Trace an arbitrary mini-program through the SAME one-device
    shard_map wrapper the shipping grid uses.

    The negative-fixture harness (tests/test_ralint.py): deliberately
    broken programs — nonlinear weight use, ``indices_are_sorted``
    without a sort, a missing ``ra.*`` scope, a wrong merge law — go
    through this exact door, so a fixture the linter misses is a real
    false negative, not a harness artifact.  ``args[weight_arg]`` must
    be a single array (the taint seed).
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel.step import _shard_map

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    wrapped = _shard_map(
        fn, mesh=mesh, in_specs=(P(),) * len(args), out_specs=P(),
    )
    closed = jax.make_jaxpr(wrapped)(*args)
    markers = list(jax.tree_util.tree_map(lambda _: False, tuple(args)))
    markers[weight_arg] = True
    flat, _ = jax.tree_util.tree_flatten(tuple(markers))
    widx = flat.index(True)
    return TracedProgram(
        spec=FixtureSpec(name=name),
        closed_jaxpr=closed,
        weight_invar_index=widx,
        output_names=tuple(output_names),
    )


def trace_program(spec: ProgramSpec) -> TracedProgram:
    """Trace one shipping program to a closed jaxpr by abstract eval.

    The wrapper is ``shard_map(core)`` over a one-device mesh: real
    enough that the collectives trace as primitives bound to the data
    axis, abstract enough that nothing compiles or touches device
    memory.  Works identically under ``JAX_PLATFORMS=cpu``.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from ..parallel.step import CORES, _shard_map

    core = functools.partial(CORES[spec.kind], **_core_kwargs(spec))
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    args = _abstract_args(spec)
    fn = _shard_map(
        core, mesh=mesh, in_specs=(P(),) * len(args), out_specs=(P(), P())
    )
    closed = jax.make_jaxpr(fn)(*args)
    # the weight plane's flat invar index: flatten a marker pytree with
    # the arguments' exact structure (valid is args[3])
    markers = jax.tree_util.tree_map(lambda _: False, args)
    markers = (*markers[:3], True, *markers[4:])
    flat, _ = jax.tree_util.tree_flatten(markers)
    widx = flat.index(True)
    assert sum(1 for f in flat if f is True) == 1
    return TracedProgram(
        spec=spec,
        closed_jaxpr=closed,
        weight_invar_index=widx,
        output_names=OUTPUT_NAMES,
    )
