"""Jaxpr-level invariant lint: taint walk + scatter/scope/merge checks.

The walker abstract-interprets a traced step program (``grid.py``) over
a five-point taint lattice seeded at the weight-plane input::

    U  untainted        independent of the weights
    G  gated            depends on weights only through predicates
                        (``weight > 0`` zero-tests) — idempotent-safe
    L  linear           a linear function of the weight plane (w itself,
                        sums/permutations of w, w times untainted data)
    N  nonlinear        anything else (w*w, weight-dependent routing,
                        linear+gated mixtures)
    O  opaque           passed through a primitive the walker cannot
                        enter (a pallas kernel) — UNPROVABLE, which is
                        a typed refusal, never a silent pass

plus a ``float_risk`` flag (the value passed through a float conversion
on a tainted path: linear but only range-exact — the matmul-counts
class) and a provenance tag set (which structural primitives — sort,
psum, pmax, all_gather, scatters — the value passed through; this is
what the sorted-scatter and merge-law checks read).

Verdicts are enforced at the **register sinks**, not at every value:

- add-law sinks (``scatter-add`` updates, ``psum`` operands): must be
  U or L without float risk.  G into an add register is exactly the
  count-one-per-row bug class (a weight-w row counts as one line);
  float risk is the f32-exactness class; N/O are nonlinear/unprovable.
- max-law sinks (``scatter-max`` updates, ``pmax`` operands): must be
  U or G.  L into a max register would make the merged value depend on
  weight magnitude — max is only correct for idempotent gates.
- scatter **indices** must be U at every sink: weight-dependent routing
  is never linear (and opaque-derived keys are unprovable).

This sink discipline is what lets the exact-counts ``add64`` carry
chain pass: the carry (``new_lo < delta``) is a predicate of two linear
values — G — but it feeds a plain ``add`` into the high word, not a
sink; the (lo, hi) pair is weight-linear at the 64-bit level, which is
the add64 law the merge-law table records (DESIGN §18).
"""

from __future__ import annotations

import dataclasses

from ..stages import STAGES, scope_of

# taint classes
U, G, L, N, O = 0, 1, 2, 3, 4
_CLS_NAME = {U: "untainted", G: "gated", L: "linear", N: "nonlinear", O: "opaque"}


@dataclasses.dataclass(frozen=True)
class Info:
    """Per-value taint state."""

    cls: int = U
    float_risk: bool = False
    prov: frozenset = frozenset()


_UINFO = Info()


def _join_cls(a: int, b: int) -> int:
    if a == U:
        return b
    if b == U:
        return a
    if O in (a, b):
        return O
    if a == b:
        return a
    return N  # {G, L} mixtures (and anything involving N)


def _merge(infos, cls: int | None = None, tag: str | None = None) -> Info:
    """Combine operand infos into one output info."""
    c = U
    fl = False
    prov = set()
    for i in infos:
        c = _join_cls(c, i.cls)
        prov |= i.prov
        fl = fl or i.float_risk
    if cls is not None:
        c = cls
    if tag is not None:
        prov.add(tag)
    return Info(cls=c, float_risk=fl and c != U, prov=frozenset(prov))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding (a violated or unprovable invariant)."""

    check: str  # {"linearity", "scatter", "scope", "merge"}
    kind: str  # e.g. "gated-into-add", "sorted-claim-without-sort"
    prim: str  # primitive (or output) name
    stage: str | None  # ra.* stage of the offending equation, if any
    #: "violation": wrong for every input; "weighted": wrong only for
    #: weighted inputs (the derived weighted-refusal set)
    severity: str
    detail: str = ""


@dataclasses.dataclass
class ProgramLint:
    """Verdicts for one traced program."""

    spec: object  # grid.ProgramSpec
    findings: list
    #: derived weight-linearity verdict: "linear" | "gated" |
    #: "float-bounded" | "unprovable" | "nonlinear"
    weight_verdict: str
    outputs: dict  # name -> {"class", "float_risk", "prov", "dtype"}
    eqns_walked: int = 0
    sinks_checked: int = 0

    @property
    def ok(self) -> bool:
        return not any(f.severity == "violation" for f in self.findings)

    @property
    def weight_safe(self) -> bool:
        return self.weight_verdict == "linear"

    def to_dict(self) -> dict:
        return {
            "program": getattr(self.spec, "name", str(self.spec)),
            "ok": self.ok,
            "weight_verdict": self.weight_verdict,
            "eqns_walked": self.eqns_walked,
            "sinks_checked": self.sinks_checked,
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "outputs": self.outputs,
        }


# -- primitive classification ------------------------------------------------

#: call-like primitives: param key holding the inner jaxpr; invars map
#: positionally onto the inner invars (after the ClosedJaxpr's consts).
_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "xla_call": "call_jaxpr",
    "named_call": "call_jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
    "remat2": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "shard_map": "jaxpr",
}

_COMPARES = {"eq", "ne", "ge", "gt", "le", "lt"}

#: multiplicative ops: two tainted operands compose nonlinearly
_MUL_LIKE = {"mul", "div", "rem", "pow", "integer_pow", "atan2", "nextafter"}

#: structural primitives whose equations must attribute to a registered
#: ra.* stage (DESIGN §14 coverage-by-construction)
_SCOPE_REQUIRED = {
    "scatter-add", "scatter-max", "scatter", "sort",
    "psum", "pmax", "all_gather", "top_k", "dot_general",
}

#: GatherScatterMode.FILL_OR_DROP — compared by name to stay independent
#: of the enum's import path across jax versions
_DROP_MODES = ("FILL_OR_DROP",)


def _stage_of(eqn) -> str | None:
    try:
        return scope_of(str(eqn.source_info.name_stack))
    except Exception:
        return None


class _Walker:
    def __init__(self):
        self.findings: list[Finding] = []
        self.eqns = 0
        self.sinks = 0

    # -- findings helpers ---------------------------------------------

    def _find(self, check, kind, eqn, severity, detail=""):
        self.findings.append(
            Finding(
                check=check, kind=kind,
                prim=eqn.primitive.name if hasattr(eqn, "primitive") else str(eqn),
                stage=_stage_of(eqn) if hasattr(eqn, "source_info") else None,
                severity=severity, detail=detail,
            )
        )

    def _check_scope(self, eqn):
        stack = str(eqn.source_info.name_stack)
        stage = scope_of(stack)
        if stage is None:
            self._find(
                "scope", "unattributed-register-update", eqn, "violation",
                f"no ra.* scope on name stack {stack!r}",
            )
        elif stage not in STAGES:
            self._find(
                "scope", "unregistered-stage", eqn, "violation",
                f"scope {stage!r} is not in the stages.py taxonomy",
            )

    def _check_add_sink(self, eqn, info: Info, what: str):
        self.sinks += 1
        if info.cls == G:
            self._find(
                "linearity", "gated-into-add", eqn, "weighted",
                f"{what} is a weight-gated value (counts one per row, "
                "not the row's weight)",
            )
        elif info.cls == N:
            self._find(
                "linearity", "nonlinear-into-add", eqn, "violation",
                f"{what} is a nonlinear function of the weight plane",
            )
        elif info.cls == O:
            self._find(
                "linearity", "opaque-into-add", eqn, "weighted",
                f"{what} passed through an opaque kernel — unprovable",
            )
        elif info.float_risk:
            self._find(
                "linearity", "float-into-add", eqn, "weighted",
                f"{what} is linear but crossed a float conversion — "
                "exact only within the float integer range",
            )

    def _check_max_sink(self, eqn, info: Info, what: str):
        self.sinks += 1
        if info.cls == L:
            self._find(
                "linearity", "linear-into-max", eqn, "weighted",
                f"{what} carries weight magnitude into a max-law "
                "register (max is only correct for idempotent gates)",
            )
        elif info.cls == N:
            self._find(
                "linearity", "nonlinear-into-max", eqn, "violation",
                f"{what} is a nonlinear function of the weight plane",
            )
        elif info.cls == O:
            self._find(
                "linearity", "opaque-into-max", eqn, "weighted",
                f"{what} passed through an opaque kernel — unprovable",
            )

    def _check_indices(self, eqn, info: Info):
        if info.cls == U:
            return
        sev = "weighted" if info.cls in (G, O) else "violation"
        kind = (
            "opaque-scatter-indices" if info.cls == O
            else "tainted-scatter-indices"
        )
        self._find(
            "linearity", kind, eqn, sev,
            f"scatter routing depends on the weight plane "
            f"({_CLS_NAME[info.cls]})",
        )

    # -- evaluation ---------------------------------------------------

    def eval_jaxpr(self, jaxpr, in_infos, const_infos=None):
        """Walk one (open) jaxpr; returns out infos."""
        env: dict = {}

        def read(v) -> Info:
            if not hasattr(v, "aval") or type(v).__name__ == "Literal":
                return _UINFO
            return env.get(v, _UINFO)

        def write(v, info):
            if type(v).__name__ != "DropVar":
                env[v] = info

        for v, i in zip(jaxpr.invars, in_infos):
            write(v, i)
        for v, i in zip(jaxpr.constvars, const_infos or []):
            write(v, i)
        for eqn in jaxpr.eqns:
            self.eqns += 1
            infos = [read(v) for v in eqn.invars]
            outs = self.eval_eqn(eqn, infos)
            for v, i in zip(eqn.outvars, outs):
                write(v, i)
        return [read(v) for v in jaxpr.outvars]

    def _eval_closed(self, closed, in_infos):
        jaxpr = getattr(closed, "jaxpr", closed)
        consts = [_UINFO] * len(jaxpr.constvars)
        return self.eval_jaxpr(jaxpr, in_infos, consts)

    def eval_eqn(self, eqn, infos) -> list:
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        # -- call-like: recurse positionally --------------------------
        if name in _CALL_PRIMS:
            inner = eqn.params.get(_CALL_PRIMS[name])
            if inner is not None:
                return self._eval_closed(inner, infos)
            return [_merge(infos)] * n_out

        if name == "cond":
            branches = eqn.params["branches"]
            pred, ops = infos[0], infos[1:]
            per_branch = [self._eval_closed(b, ops) for b in branches]
            outs = []
            for outs_i in zip(*per_branch):
                m = _merge(outs_i)
                if pred.cls != U:
                    # branch selection by a weight-derived predicate:
                    # same composition rule as select_n
                    m = _merge([m], cls=G if m.cls == U else N)
                    m = Info(m.cls, m.float_risk, m.prov | pred.prov)
                outs.append(m)
            return outs

        if name == "while":
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            body = eqn.params["body_jaxpr"]
            carry = list(infos[cn + bn:])
            bconsts = infos[cn:cn + bn]
            for _ in range(len(carry) + 2):  # monotone fixpoint
                outs = self._eval_closed(body, bconsts + carry)
                new = [_merge([a, b]) for a, b in zip(carry, outs)]
                if all(n == c for n, c in zip(new, carry)):
                    break
                carry = new
            return carry

        if name == "scan":
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            body = eqn.params["jaxpr"]
            consts = infos[:nc]
            carry = list(infos[nc:nc + ncar])
            xs = infos[nc + ncar:]
            ys = None
            for _ in range(len(carry) + 2):
                outs = self._eval_closed(body, consts + carry + xs)
                new = [_merge([a, b]) for a, b in zip(carry, outs[:ncar])]
                ys = outs[ncar:]
                if all(n == c for n, c in zip(new, carry)):
                    break
                carry = new
            return carry + list(ys or [])

        # -- register sinks -------------------------------------------
        if name in ("scatter-add", "scatter-max", "scatter"):
            operand, indices, updates = infos[0], infos[1], infos[2]
            self._check_scope(eqn)
            self._check_indices(eqn, indices)
            mode = eqn.params.get("mode")
            if getattr(mode, "name", str(mode)) not in _DROP_MODES:
                self._find(
                    "scatter", "scatter-not-drop", eqn, "violation",
                    f"scatter mode is {mode!r}, not FILL_OR_DROP "
                    "(mode='drop'): out-of-bounds keys would clip or be "
                    "undefined instead of dropping",
                )
            if eqn.params.get("indices_are_sorted") and "sort" not in indices.prov:
                self._find(
                    "scatter", "sorted-claim-without-sort", eqn, "violation",
                    "indices_are_sorted=True but the index chain contains "
                    "no lax.sort",
                )
            if name == "scatter-add":
                self._check_add_sink(eqn, updates, "scatter-add updates")
            elif name == "scatter-max":
                self._check_max_sink(eqn, updates, "scatter-max updates")
            elif updates.cls != U:
                self._find(
                    "linearity", "tainted-into-set", eqn, "violation",
                    "weight-derived value scattered with overwrite "
                    "semantics (neither add- nor max-law)",
                )
            out = _merge([operand, updates, indices], tag=name)
            if indices.cls != U:
                out = _merge([out], cls=_join_cls(out.cls, O if indices.cls == O else N))
            return [out] * n_out

        if name == "psum":
            self._check_scope(eqn)
            outs = []
            for i in infos:
                self._check_add_sink(eqn, i, "psum operand")
                outs.append(_merge([i], tag="psum"))
            return outs

        if name == "pmax":
            self._check_scope(eqn)
            outs = []
            for i in infos:
                self._check_max_sink(eqn, i, "pmax operand")
                outs.append(_merge([i], tag="pmax"))
            return outs

        if name == "all_gather":
            self._check_scope(eqn)
            return [_merge([i], tag="all_gather") for i in infos]

        if name == "sort":
            self._check_scope(eqn)
            num_keys = eqn.params.get("num_keys", 1)
            keys_tainted = any(i.cls != U for i in infos[:num_keys])
            outs = []
            for i in infos:
                if keys_tainted:
                    outs.append(_merge(infos, cls=N, tag="sort"))
                else:
                    outs.append(_merge([i], tag="sort"))
            return outs

        if name == "dot_general":
            self._check_scope(eqn)
            a, b = infos[0], infos[1]
            if a.cls == U and b.cls == U:
                return [_merge(infos)] * n_out
            if O in (a.cls, b.cls):
                return [_merge(infos, cls=O)] * n_out
            if a.cls != U and b.cls != U:
                return [_merge(infos, cls=N)] * n_out
            t = a if a.cls != U else b
            # contraction sums gated values -> counts rows, not weights
            cls = L if t.cls == L else N
            return [_merge(infos, cls=cls)] * n_out

        if name == "top_k":
            self._check_scope(eqn)
            cls = U if all(i.cls == U for i in infos) else N
            return [_merge(infos, cls=cls)] * n_out

        # -- everything else: dataflow rules --------------------------
        if name in _COMPARES:
            if any(i.cls == O for i in infos):
                return [_merge(infos, cls=O)] * n_out
            cls = G if any(i.cls != U for i in infos) else U
            return [_merge(infos, cls=cls)] * n_out

        if name in _MUL_LIKE:
            a, b = infos[0], infos[1] if len(infos) > 1 else _UINFO
            if a.cls == L and b.cls == L:
                return [_merge(infos, cls=N)] * n_out
            return [_merge(infos)] * n_out

        if name == "select_n":
            pred, cases = infos[0], infos[1:]
            m = _merge(cases)
            if pred.cls == U:
                return [m] * n_out
            cls = O if O in (pred.cls, m.cls) else (G if m.cls == U else N)
            return [_merge(infos, cls=cls)] * n_out

        if name in ("reduce_sum", "cumsum"):
            i = _merge(infos)
            if i.cls == G:
                i = _merge(infos, cls=N)  # sum of gates counts rows
            if self._tainted_reduce_needs_scope(infos):
                self._check_scope(eqn)
            return [i] * n_out

        if name in ("reduce_max", "reduce_min", "cummax", "cummin"):
            i = _merge(infos)
            if i.cls == L:
                i = _merge(infos, cls=N)  # magnitude extremum of weights
            if self._tainted_reduce_needs_scope(infos):
                self._check_scope(eqn)
            return [i] * n_out

        if name in ("argmax", "argmin", "reduce_precision"):
            cls = U if all(i.cls == U for i in infos) else N
            return [_merge(infos, cls=cls)] * n_out

        if name in ("gather", "dynamic_slice", "dynamic_update_slice", "take"):
            operand, rest = infos[0], infos[1:]
            routing = _merge(rest)
            if routing.cls != U:
                cls = O if O in (routing.cls, operand.cls) else N
                return [_merge(infos, cls=cls)] * n_out
            return [_merge(infos)] * n_out

        if name == "convert_element_type":
            i = _merge(infos)
            if i.cls != U:
                import numpy as np

                try:
                    kind = np.dtype(eqn.params["new_dtype"]).kind
                except TypeError:
                    kind = "?"
                if kind in "fc":
                    # a tainted value crossing into float: linear maybe,
                    # but exact only within the float integer range —
                    # the matmul-counts refusal class
                    i = Info(i.cls, True, i.prov)
            return [i] * n_out

        if eqn.params and any(
            hasattr(v, "eqns") or hasattr(getattr(v, "jaxpr", None), "eqns")
            for k, v in eqn.params.items()
            if k != "update_jaxpr"
        ):
            # an unrecognized primitive CARRYING a program (pallas_call,
            # a future custom call): opaque — unprovable, never entered
            if any(i.cls != U for i in infos):
                return [_merge(infos, cls=O, tag=f"opaque:{name}")] * n_out
            return [_merge(infos, tag=f"opaque:{name}")] * n_out

        # default: transparent elementwise/structural op
        return [_merge(infos)] * n_out

    @staticmethod
    def _tainted_reduce_needs_scope(infos) -> bool:
        return any(i.cls in (G, L, N, O) for i in infos)


#: merge-law table: output register -> (dtype, required collective,
#: forbidden collective, law name).  counts_lo/hi form the add64 pair
#: (uint32 lo/hi with carry — exact past 2^32 while per-chunk deltas
#: stay below config.WEIGHTED_CHUNK_WEIGHT_LIMIT); cms/talk_cms are
#: add32 mod-2^32 sketch planes; hll merges by idempotent max.
OUTPUT_LAWS = {
    "counts_lo": ("uint32", "psum", "pmax", "add64"),
    "counts_hi": ("uint32", "psum", "pmax", "add64"),
    "cms": ("uint32", "psum", "pmax", "add32"),
    "talk_cms": ("uint32", "psum", "pmax", "add32"),
    "hll": ("uint32", "pmax", "psum", "max"),
    "cand_acl": ("uint32", "all_gather", None, "gather"),
    "cand_src": ("uint32", "all_gather", None, "gather"),
    "cand_est": ("uint32", "all_gather", None, "gather"),
}


def lint_program(traced) -> ProgramLint:
    """Run every jaxpr-level check over one traced program."""
    closed = traced.closed_jaxpr
    jaxpr = closed.jaxpr
    walker = _Walker()
    in_infos = [
        Info(cls=L) if i == traced.weight_invar_index else _UINFO
        for i in range(len(jaxpr.invars))
    ]
    out_infos = walker.eval_jaxpr(
        jaxpr, in_infos, [_UINFO] * len(jaxpr.constvars)
    )

    spec = traced.spec
    outputs = {}
    for name, var, info in zip(traced.output_names, jaxpr.outvars, out_infos):
        dtype = str(getattr(getattr(var, "aval", None), "dtype", "?"))
        outputs[name] = {
            "class": _CLS_NAME[info.cls],
            "float_risk": info.float_risk,
            "prov": sorted(info.prov),
            "dtype": dtype,
        }
        law = OUTPUT_LAWS.get(name)
        if law is None:
            continue
        want_dtype, required, forbidden, law_name = law
        exempt = (
            name in ("counts_lo", "counts_hi")
            and not getattr(spec, "exact_counts", True)
        )
        if exempt:
            continue
        if dtype != want_dtype:
            walker.findings.append(Finding(
                "merge", "register-dtype", f"output:{name}", None,
                "violation",
                f"{name} is {dtype}, law {law_name} requires {want_dtype}",
            ))
        if required not in info.prov:
            walker.findings.append(Finding(
                "merge", "missing-merge-seam", f"output:{name}", None,
                "violation",
                f"{name} never crossed its {required} merge seam "
                f"(law {law_name})",
            ))
        if forbidden is not None and forbidden in info.prov:
            walker.findings.append(Finding(
                "merge", "wrong-merge-law", f"output:{name}", None,
                "violation",
                f"{name} crossed {forbidden}, which is not its law "
                f"({law_name})",
            ))

    verdict = "linear"
    kinds = {f.kind for f in walker.findings if f.check == "linearity"}
    if kinds & {"nonlinear-into-add", "nonlinear-into-max",
                "tainted-scatter-indices", "tainted-into-set"}:
        verdict = "nonlinear"
    elif kinds & {"opaque-into-add", "opaque-into-max",
                  "opaque-scatter-indices"}:
        verdict = "unprovable"
    elif "gated-into-add" in kinds or "linear-into-max" in kinds:
        verdict = "gated"
    elif "float-into-add" in kinds:
        verdict = "float-bounded"

    return ProgramLint(
        spec=spec,
        findings=walker.findings,
        weight_verdict=verdict,
        outputs=outputs,
        eqns_walked=walker.eqns,
        sinks_checked=walker.sinks,
    )
