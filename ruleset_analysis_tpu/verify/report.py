"""Assemble + render the lint report (CLI ``lint`` / tools/ralint.py).

One entry point, :func:`run_lint`, runs the program grid through the
jaxpr linter, cross-checks the derived weighted-refusal set against the
declarative table in ``config.py`` (the no-drift guarantee), runs the
repo registry auditor, and folds everything into a :class:`LintReport`
that renders as text or JSON.
"""

from __future__ import annotations

import dataclasses

from .grid import ProgramSpec, fast_grid, shipping_grid, trace_program
from .jaxpr_lint import Finding, ProgramLint, lint_program
from .registry import AuditFinding, audit_registry


def expected_weighted_refusal(spec: ProgramSpec) -> str | None:
    """The table's verdict for this spec, or None (= weighted accepted).

    The SAME declarative table the runtime refusal path reads
    (``config.WEIGHTED_INPUT_REFUSALS``), keyed by the spec's effective
    AnalysisConfig field values.
    """
    from ..config import WEIGHTED_INPUT_REFUSALS

    kw = spec.config_kwargs()
    for r in WEIGHTED_INPUT_REFUSALS:
        if kw.get(r.field) == r.value:
            return r.lint_verdict
    return None


#: derived-verdict -> table-verdict vocabulary: the walker says
#: "unprovable"/"float-bounded"/"gated"/"nonlinear", the table registers
#: the refusal class it expects the walker to derive.
_DERIVED_TO_TABLE = {
    "unprovable": "unprovable",
    "float-bounded": "float-bounded",
    "gated": "gated",
    "nonlinear": "nonlinear",
}


@dataclasses.dataclass
class LintReport:
    programs: list  # [ProgramLint]
    table_drift: list  # [Finding] derived-vs-table mismatches
    registry: list  # [AuditFinding]
    grid: str  # "full" | "fast"

    @property
    def ok(self) -> bool:
        return (
            all(p.ok for p in self.programs)
            and not self.table_drift
            and not self.registry
        )

    @property
    def violations(self) -> int:
        return sum(
            1
            for p in self.programs
            for f in p.findings
            if f.severity == "violation"
        )

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "grid": self.grid,
            "programs": [p.to_dict() for p in self.programs],
            "table_drift": [dataclasses.asdict(f) for f in self.table_drift],
            "registry": [dataclasses.asdict(f) for f in self.registry],
        }


def check_table_drift(lints: list) -> list:
    """Derived weighted-refusal set == the declarative table, exactly.

    Any mismatch — a program the walker cannot prove that the table
    accepts, or a table refusal the walker proves linear — is drift:
    either the table is stale or an impl silently changed its math.
    """
    drift: list[Finding] = []
    for pl in lints:
        derived = pl.weight_verdict
        expected = expected_weighted_refusal(pl.spec)
        if expected is None:
            if derived != "linear":
                drift.append(Finding(
                    check="table", kind="underrefusal",
                    prim=pl.spec.name, stage=None, severity="violation",
                    detail=(
                        f"linter derives {derived!r} but "
                        "config.WEIGHTED_INPUT_REFUSALS accepts this "
                        "combination for weighted inputs"
                    ),
                ))
        elif _DERIVED_TO_TABLE.get(derived) != expected:
            drift.append(Finding(
                check="table", kind="overrefusal" if derived == "linear"
                else "verdict-mismatch",
                prim=pl.spec.name, stage=None, severity="violation",
                detail=(
                    f"table expects {expected!r} for this combination, "
                    f"linter derives {derived!r}"
                ),
            ))
    return drift


def run_lint(
    *,
    full: bool = True,
    registry: bool = True,
    repo_root: str | None = None,
    specs: list | None = None,
) -> LintReport:
    """Trace + lint the grid, cross-check the table, audit registries."""
    if specs is None:
        specs = shipping_grid() if full else fast_grid()
    lints = [lint_program(trace_program(s)) for s in specs]
    drift = check_table_drift(lints)
    audits = audit_registry(repo_root) if registry else []
    return LintReport(
        programs=lints,
        table_drift=drift,
        registry=audits,
        grid="full" if full else "fast",
    )


def render_text(report: LintReport) -> str:
    out = []
    n_lin = sum(1 for p in report.programs if p.weight_verdict == "linear")
    out.append(
        f"ralint: {len(report.programs)} step programs traced "
        f"({report.grid} grid, abstract eval only)"
    )
    for p in report.programs:
        viols = [f for f in p.findings if f.severity == "violation"]
        weighted = [f for f in p.findings if f.severity == "weighted"]
        mark = "ok " if p.ok else "FAIL"
        out.append(
            f"  [{mark}] {p.spec.name:55s} weight={p.weight_verdict:13s} "
            f"sinks={p.sinks_checked:3d} eqns={p.eqns_walked}"
        )
        for f in viols:
            out.append(
                f"         VIOLATION {f.check}/{f.kind} at {f.prim}"
                f"{' [' + f.stage + ']' if f.stage else ''}: {f.detail}"
            )
        for f in weighted:
            out.append(f"         weighted-refusal {f.kind} at {f.prim}")
    out.append(
        f"weight-linearity: {n_lin}/{len(report.programs)} programs proven "
        "linear; the rest are typed weighted-input refusals"
    )
    if report.table_drift:
        out.append("TABLE DRIFT (derived verdicts vs config.WEIGHTED_INPUT_REFUSALS):")
        for f in report.table_drift:
            out.append(f"  {f.kind}: {f.prim}: {f.detail}")
    else:
        out.append(
            "refusal table: derived verdicts match "
            "config.WEIGHTED_INPUT_REFUSALS exactly"
        )
    if report.registry:
        out.append(f"registry audit: {len(report.registry)} finding(s)")
        for f in report.registry:
            out.append(f"  [{f.registry}] {f.kind}: {f.subject} — {f.detail}")
    else:
        out.append("registry audit: clean (faults / cli+README+PARITY / volatile)")
    out.append("RESULT: " + ("PASS" if report.ok else "FAIL"))
    return "\n".join(out)
