"""Repo-level registry auditor — the drift the jaxprs cannot see.

Three registries pair a declaration site with scattered consumption
sites, and nothing structural kept them in sync until now:

- **fault sites** (``runtime/faults.py::SITES``) <-> armed ``fire()``
  call sites in the package <-> test coverage (a registered site no
  test ever fires is an untested failure mode; a ``fire()`` naming an
  unregistered site can never fire at all);
- **CLI flags** (``cli.make_parser()``) <-> README documentation <->
  PARITY subcommand rows (an undocumented flag is invisible to
  operators; README mentions of flags that no longer exist mislead);
- **VOLATILE totals keys** (``runtime/report.py::VOLATILE_TOTALS`` —
  the keys report-identity tests strip) <-> the runtime code that
  actually produces those totals (a volatile key nothing produces is
  dead weight; a test module keeping its own private list can drift);
- **retry sites** (``runtime/retrypolicy.py::RETRY_SITES``) <-> the
  policy table <-> ``retrypolicy.call()`` call sites <-> chaos
  coverage: every registered seam must have a policy entry, a
  transient chaos schedule (``fault_site@N:k`` with single-digit k —
  below the attempt bound, so the schedule proves RECOVERY), and a
  permanent-escalation test (``fault_site@N:kk`` with k >= 10 — past
  any attempt bound, so the schedule proves the typed escalation).

Pure stdlib + argparse introspection: no device, no jax import beyond
what ``cli`` itself pulls in.
"""

from __future__ import annotations

import dataclasses
import os
import re


@dataclasses.dataclass(frozen=True)
class AuditFinding:
    registry: str  # {"faults", "cli", "volatile"}
    kind: str
    subject: str
    detail: str = ""


def _repo_root(explicit: str | None = None) -> str:
    if explicit:
        return os.path.abspath(explicit)
    # ruleset_analysis_tpu/verify/registry.py -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _read(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return ""


def _py_files(root: str, subdir: str) -> list[str]:
    out = []
    base = os.path.join(root, subdir)
    for dirpath, _dirs, files in os.walk(base):
        for f in files:
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


_FIRE_RE = re.compile(r"""fire\(\s*\n?\s*["']([a-z0-9_.]+)["']""")


def audit_faults(root: str | None = None) -> list[AuditFinding]:
    """SITES <-> armed fire() call sites <-> test coverage."""
    from ..runtime.faults import SITES

    root = _repo_root(root)
    findings: list[AuditFinding] = []
    fired: set[str] = set()
    for path in _py_files(root, "ruleset_analysis_tpu"):
        if path.endswith(os.path.join("runtime", "faults.py")):
            continue
        for m in _FIRE_RE.finditer(_read(path)):
            fired.add(m.group(1))
    tests_text = "".join(_read(p) for p in _py_files(root, "tests"))
    for site in sorted(SITES):
        if site not in fired:
            findings.append(AuditFinding(
                "faults", "registered-never-armed", site,
                "no faults.fire() call site names this registered site",
            ))
        if site not in tests_text:
            findings.append(AuditFinding(
                "faults", "registered-never-tested", site,
                "no test schedules or references this fault site",
            ))
    for site in sorted(fired - set(SITES)):
        findings.append(AuditFinding(
            "faults", "armed-unregistered", site,
            "fire() names a site missing from SITES — it can never fire",
        ))
    return findings


def _cli_flags():
    """(subcommand, long-flag) pairs + subcommand list from the parser."""
    import argparse

    from ..cli import make_parser

    ap = make_parser()
    subs = next(
        a for a in ap._actions if isinstance(a, argparse._SubParsersAction)
    )
    flags = set()
    for name, sp in subs.choices.items():
        for act in sp._actions:
            for o in act.option_strings:
                if o.startswith("--") and o != "--help":
                    flags.add((name, o))
    return sorted(subs.choices), sorted(flags)


def audit_cli(root: str | None = None) -> list[AuditFinding]:
    """CLI flags <-> README; subcommands <-> README + PARITY."""
    root = _repo_root(root)
    findings: list[AuditFinding] = []
    readme = _read(os.path.join(root, "README.md"))
    parity = _read(os.path.join(root, "PARITY.md"))
    subcommands, flags = _cli_flags()
    for name, flag in flags:
        if flag not in readme:
            findings.append(AuditFinding(
                "cli", "flag-undocumented", f"{name} {flag}",
                "flag absent from README.md",
            ))
    for name in subcommands:
        if name not in readme:
            findings.append(AuditFinding(
                "cli", "subcommand-undocumented", name,
                "subcommand absent from README.md",
            ))
        if name not in parity:
            findings.append(AuditFinding(
                "cli", "subcommand-no-parity-row", name,
                "subcommand absent from PARITY.md",
            ))
    return findings


_LOCAL_VOLATILE_RE = re.compile(r"^VOLATILE\s*=\s*\(", re.M)


def audit_volatile(root: str | None = None) -> list[AuditFinding]:
    """VOLATILE_TOTALS <-> totals producers <-> per-test-module drift."""
    from ..runtime.report import VOLATILE_TOTALS

    root = _repo_root(root)
    findings: list[AuditFinding] = []
    runtime_text = "".join(
        _read(p) for p in _py_files(root, "ruleset_analysis_tpu")
    )
    for key in VOLATILE_TOTALS:
        # a volatile key must correspond to a real totals producer
        # somewhere in the runtime (dict literal key or totals[...] set)
        if f'"{key}"' not in runtime_text and f"'{key}'" not in runtime_text:
            findings.append(AuditFinding(
                "volatile", "volatile-key-never-produced", key,
                "VOLATILE_TOTALS names a totals key no runtime code "
                "produces",
            ))
    for path in _py_files(root, "tests"):
        if _LOCAL_VOLATILE_RE.search(_read(path)):
            findings.append(AuditFinding(
                "volatile", "local-volatile-list", os.path.basename(path),
                "test module defines its own VOLATILE tuple instead of "
                "importing runtime.report.VOLATILE_TOTALS — lists drift",
            ))
    return findings


_RETRY_CALL_RE = re.compile(
    r"""retrypolicy\.call\(\s*\n?\s*["']([a-z0-9_.]+)["']"""
)


def audit_retry(root: str | None = None) -> list[AuditFinding]:
    """RETRY_SITES <-> policy table <-> call sites <-> chaos coverage.

    The chaos-coverage convention is positional in the schedule string:
    ``site@N:k`` with a SINGLE-digit k is a transient schedule (k below
    every attempt bound — the harness asserts recovery + bit-identity),
    while k with two or more digits (the suites use ``:99``) is a
    budget-exhaustion schedule (the harness asserts the escalation
    stays typed).  Tests therefore declare their schedules as literal
    strings; this audit greps for them.
    """
    from ..runtime.faults import SITES
    from ..runtime.retrypolicy import DEFAULT_POLICIES, RETRY_SITES

    root = _repo_root(root)
    findings: list[AuditFinding] = []
    called: set[str] = set()
    for path in _py_files(root, "ruleset_analysis_tpu"):
        if path.endswith(os.path.join("runtime", "retrypolicy.py")):
            continue
        for m in _RETRY_CALL_RE.finditer(_read(path)):
            called.add(m.group(1))
    tests_text = "".join(_read(p) for p in _py_files(root, "tests"))
    for site, meta in sorted(RETRY_SITES.items()):
        if site not in DEFAULT_POLICIES:
            findings.append(AuditFinding(
                "retry", "site-without-policy", site,
                "RETRY_SITES entry has no DEFAULT_POLICIES row",
            ))
        if site not in called:
            findings.append(AuditFinding(
                "retry", "registered-never-called", site,
                "no retrypolicy.call() site names this registered seam",
            ))
        if meta.fault_site not in SITES:
            findings.append(AuditFinding(
                "retry", "fault-site-unregistered", site,
                f"maps to fault site {meta.fault_site!r} missing from "
                "faults.SITES",
            ))
        fs = re.escape(meta.fault_site)
        transient_ks = [
            int(k)
            for k in re.findall(fs + r"@\d+:([1-9])(?!\d)", tests_text)
        ]
        if not transient_ks:
            findings.append(AuditFinding(
                "retry", "no-transient-schedule", site,
                f"no test schedules {meta.fault_site}@N:k (single-digit "
                "k) — the recovery half of the seam is untested",
            ))
        elif (
            site in DEFAULT_POLICIES
            and min(transient_ks) >= DEFAULT_POLICIES[site].attempts
        ):
            # a "transient" schedule at or past the attempt budget never
            # recovers in place — it silently tests the escalation path
            # twice and the recovery path not at all
            findings.append(AuditFinding(
                "retry", "transient-schedule-exceeds-budget", site,
                f"every {meta.fault_site}@N:k schedule has k >= the "
                f"policy's {DEFAULT_POLICIES[site].attempts} attempts — "
                "no test proves in-place recovery",
            ))
        if not re.search(fs + r"@\d+:\d{2,}", tests_text):
            findings.append(AuditFinding(
                "retry", "no-escalation-test", site,
                f"no test schedules {meta.fault_site}@N:kk (k >= 10) — "
                "budget exhaustion escalating typed is untested",
            ))
    for site in sorted(called - set(RETRY_SITES)):
        findings.append(AuditFinding(
            "retry", "called-unregistered", site,
            "retrypolicy.call() names a site missing from RETRY_SITES",
        ))
    return findings


_DUMP_RE = re.compile(
    r"""flightrec\.(?:dump|seal)\(\s*\n?\s*["']([a-z-]+)["']"""
)


def audit_observability(root: str | None = None) -> list[AuditFinding]:
    """Flight-recorder triggers <-> dump sites <-> tests; gauge parity.

    Two halves (ISSUE 15 satellite):

    1. **Dump triggers.**  Every literal trigger passed to
       ``flightrec.dump``/``seal`` in the package must be registered in
       ``flightrec.TRIGGERS``; the error-classified triggers
       (abort/stall/unhandled) are verified FUNCTIONALLY through
       ``classify`` so a registry/classifier drift fails here; and every
       registered trigger must appear in the test suite — an untested
       crash path fails ``make lint`` instead of failing an operator.

    2. **Gauge/histogram parity.**  The serve ``/metrics`` endpoint
       renders the SAME latency histogram as JSON percentile gauges and
       as a Prometheus histogram; this audit drives a synthetic
       histogram through both renderings and fails on any divergence
       (missing percentile keys, non-cumulative buckets, a
       bucket-derived p99 that disagrees with the JSON gauge, or a
       numeric gauge the prom gauge rendering drops).
    """
    from ..errors import AnalysisError, StallError
    from ..runtime import flightrec
    from ..runtime.autoscale import render_prom
    from ..runtime.metrics import LatencyHistogram, quantile_from_prom

    root = _repo_root(root)
    findings: list[AuditFinding] = []

    # -- half 1: triggers ------------------------------------------------
    dumped: set[str] = set()
    for path in _py_files(root, "ruleset_analysis_tpu"):
        if path.endswith(os.path.join("runtime", "flightrec.py")):
            continue
        for m in _DUMP_RE.finditer(_read(path)):
            dumped.add(m.group(1))
    for trig in sorted(dumped - set(flightrec.TRIGGERS)):
        findings.append(AuditFinding(
            "observability", "dump-trigger-unregistered", trig,
            "flightrec.dump()/seal() names a trigger missing from "
            "TRIGGERS — the dump would raise instead of recording",
        ))
    for exc, want in (
        (StallError("x"), "stall"),
        (AnalysisError("x"), "abort"),
        (ValueError("x"), "unhandled"),
    ):
        got = flightrec.classify(exc)
        if got != want or got not in flightrec.TRIGGERS:
            findings.append(AuditFinding(
                "observability", "classifier-registry-drift",
                type(exc).__name__,
                f"classify() maps to {got!r}; expected registered "
                f"trigger {want!r}",
            ))
    tests_text = "".join(_read(p) for p in _py_files(root, "tests"))
    for trig in sorted(flightrec.TRIGGERS):
        if f'"{trig}"' not in tests_text and f"'{trig}'" not in tests_text:
            findings.append(AuditFinding(
                "observability", "trigger-never-tested", trig,
                "no test exercises or references this dump trigger",
            ))

    # -- half 2: gauge/histogram parity ----------------------------------
    hist = LatencyHistogram()
    for us in (3, 40, 40, 500, 2_000, 2_000, 2_000, 70_000, 900_000, 12_000_000):
        hist.record(us * 1e-6)
    gauges = hist.gauges("latency_probe_")
    for key in ("latency_probe_p50_sec", "latency_probe_p90_sec",
                "latency_probe_p99_sec", "latency_probe_count"):
        if key not in gauges:
            findings.append(AuditFinding(
                "observability", "latency-gauge-missing", key,
                "histogram gauges() dropped a required /metrics key",
            ))
    prom_gauges = render_prom(gauges, prefix="ra_serve_")
    for key, v in gauges.items():
        if isinstance(v, (int, float)) and f"ra_serve_{key}" not in prom_gauges:
            findings.append(AuditFinding(
                "observability", "gauge-prom-drift", key,
                "a numeric /metrics JSON gauge is absent from the "
                "Prometheus gauge rendering",
            ))
    name = "ra_probe_seconds"
    prom = hist.render_prom(name)
    cums = [
        int(line.rsplit(" ", 1)[1])
        for line in prom.splitlines()
        if line.startswith(f"{name}_bucket")
    ]
    if any(b < a for a, b in zip(cums, cums[1:])):
        findings.append(AuditFinding(
            "observability", "histogram-not-cumulative", name,
            "prom bucket counts must be non-decreasing in le order",
        ))
    if not cums or cums[-1] != hist.count or f"{name}_count {hist.count}" not in prom:
        findings.append(AuditFinding(
            "observability", "histogram-count-drift", name,
            "prom +Inf bucket / _count disagree with the histogram count",
        ))
    for p, key in ((0.5, "p50_sec"), (0.9, "p90_sec"), (0.99, "p99_sec")):
        if quantile_from_prom(prom, name, p) != gauges[f"latency_probe_{key}"]:
            findings.append(AuditFinding(
                "observability", "histogram-quantile-drift", key,
                "the prom-bucket-derived quantile disagrees with the "
                "JSON gauge of the same histogram",
            ))

    # -- half 3: build-info + SLO burn-rate parity (ISSUE 19 satellite) --
    from ..runtime.autoscale import render_prom_labeled
    from ..runtime.metrics import (
        SloBurnEngine, SloPolicy, build_info, render_build_info_prom,
    )

    info = build_info({"mesh": "probe/2"})
    for key in ("version", "jax", "simd", "mesh"):
        if not info.get(key):
            findings.append(AuditFinding(
                "observability", "build-info-key-missing", key,
                "build_info() dropped a required label — the "
                "ra_build_info gauge would not identify the build",
            ))
    bi_prom = render_build_info_prom(info)
    if "ra_build_info{" not in bi_prom or not bi_prom.rstrip().endswith("} 1"):
        findings.append(AuditFinding(
            "observability", "build-info-prom-shape", "ra_build_info",
            "render_build_info_prom() must expose exactly one "
            "ra_build_info{...} 1 gauge line",
        ))
    for k, v in info.items():
        if f'{k}="{v}"' not in bi_prom:
            findings.append(AuditFinding(
                "observability", "build-info-prom-drift", k,
                "a build_info() JSON label is absent from the "
                "ra_build_info prom labels — JSON and prom disagree "
                "about the build identity",
            ))

    slo = SloBurnEngine(SloPolicy.parse("p99_publish_ms<=500,drop_rate<=0.001"))
    slo.observe({"p99_publish_ms": 900.0, "drop_rate": 0.5})
    slo_prom = render_prom(slo.gauges(), prefix="ra_serve_")
    for key, v in slo.gauges().items():
        if isinstance(v, (int, float)) and f"ra_serve_{key}" not in slo_prom:
            findings.append(AuditFinding(
                "observability", "slo-gauge-prom-drift", key,
                "a numeric SLO JSON gauge is absent from the prom "
                "gauge rendering",
            ))
    labeled = slo.labeled_gauges()
    slo_lab_prom = render_prom_labeled(
        labeled, prefix="ra_serve_", label="objective"
    )
    for objective, lg in labeled.items():
        for key, v in lg.items():
            if not isinstance(v, (int, float)):
                continue
            series = f'ra_serve_{key}{{objective="{objective}"}}'
            if series not in slo_lab_prom:
                findings.append(AuditFinding(
                    "observability", "slo-labeled-prom-drift",
                    f"{objective}/{key}",
                    "a per-objective SLO JSON gauge has no "
                    "objective-labeled prom series — scrapers and the "
                    "JSON endpoint would disagree",
                ))
    return findings


def audit_tenancy(root: str | None = None) -> list[AuditFinding]:
    """Tenancy-plane drift: step-core grid, labeled series, WAL format.

    Three halves (ISSUE 16 satellite):

    1. **Core/grid registry.**  Every step core in
       ``parallel/step.py::CORES`` must appear as a program kind in the
       lint grid (``verify/grid.py::shipping_grid``) and vice versa — a
       core the jaxpr linter never traces ships unverified; a grid kind
       with no core can never have been a shipping program.

    2. **Labeled-series parity.**  The multi-tenant ``/metrics``
       endpoint renders per-tenant JSON gauge blocks AND
       tenant-labeled Prometheus series from the same numbers; this
       audit drives synthetic per-tenant gauges + histograms through
       both renderings and fails on a dropped labeled series, a label
       collision between tenants, or a labeled-bucket quantile that
       disagrees with the JSON gauge.

    3. **WAL record-format compat.**  The tenancy plane bumped the WAL
       segment format (v2 carries the tenant key per record); this
       audit round-trips a v2 record functionally and hand-writes a v1
       segment to prove pre-tenancy spools still replay — under
       ``DEFAULT_TENANT`` — instead of quarantining.
    """
    import struct
    import tempfile
    import zlib

    from ..parallel.step import CORES
    from ..runtime.autoscale import render_prom_labeled
    from ..runtime.metrics import LatencyHistogram, quantile_from_prom
    from ..runtime import wal as wal_mod

    root = _repo_root(root)
    findings: list[AuditFinding] = []

    # -- half 1: CORES <-> lint-grid kinds -------------------------------
    from .grid import shipping_grid

    grid_kinds = {s.kind for s in shipping_grid()}
    for kind in sorted(set(CORES) - grid_kinds):
        findings.append(AuditFinding(
            "tenancy", "core-not-in-lint-grid", kind,
            "parallel/step.py::CORES entry has no shipping_grid() "
            "program — the jaxpr linter never traces it",
        ))
    for kind in sorted(grid_kinds - set(CORES)):
        findings.append(AuditFinding(
            "tenancy", "grid-kind-without-core", kind,
            "lint grid names a program kind missing from CORES",
        ))

    # -- half 2: tenant-labeled series parity ----------------------------
    per_tenant = {
        "acme": {"lines_routed_total": 7, "windows_published": 2},
        "globex": {"lines_routed_total": 11, "windows_published": 3},
    }
    labeled = render_prom_labeled(per_tenant, prefix="ra_serve_tenant_",
                                  label="tenant")
    for tenant, gauges in per_tenant.items():
        for key, v in gauges.items():
            want = f'ra_serve_tenant_{key}{{tenant="{tenant}"}} {v}'
            if want not in labeled:
                findings.append(AuditFinding(
                    "tenancy", "labeled-gauge-drift", f"{tenant}/{key}",
                    "a per-tenant JSON gauge is absent from the "
                    "tenant-labeled Prometheus rendering",
                ))
    hists = {}
    for i, tenant in enumerate(("acme", "globex")):
        h = LatencyHistogram()
        for us in (5, 90 * (i + 1), 4_000, 250_000 * (i + 1)):
            h.record(us * 1e-6)
        hists[tenant] = h
    name = "ra_serve_tenant_probe_seconds"
    text = "".join(
        h.render_prom(name, labels={"tenant": t}) for t, h in hists.items()
    )
    for tenant, h in hists.items():
        g = h.gauges("latency_probe_")
        for p, key in ((0.5, "p50_sec"), (0.99, "p99_sec")):
            got = quantile_from_prom(text, name, p,
                                     labels={"tenant": tenant})
            if got != g[f"latency_probe_{key}"]:
                findings.append(AuditFinding(
                    "tenancy", "labeled-quantile-drift",
                    f"{tenant}/{key}",
                    "the labeled prom-bucket quantile disagrees with "
                    "the same tenant's JSON gauge — label selection "
                    "is picking up another tenant's buckets",
                ))

    # -- half 3: WAL v1 -> v2 record-format compatibility ----------------
    if wal_mod.MAGIC == wal_mod.MAGIC2:
        findings.append(AuditFinding(
            "tenancy", "wal-magic-collision", "MAGIC2",
            "the v2 segment magic must differ from v1",
        ))
    with tempfile.TemporaryDirectory(prefix="ra-audit-wal-") as td:
        w = wal_mod.WriteAheadLog(td)
        w.append("alpha line", tenant="acme")
        w.append("beta line")
        w.close()
        got = [(line, tenant) for _seq, line, tenant in
               wal_mod.WriteAheadLog(td).replay(0)]
        if got != [("alpha line", "acme"),
                   ("beta line", wal_mod.DEFAULT_TENANT)]:
            findings.append(AuditFinding(
                "tenancy", "wal-v2-roundtrip-drift", "replay",
                f"v2 append/replay lost the tenant key: {got!r}",
            ))
    with tempfile.TemporaryDirectory(prefix="ra-audit-wal1-") as td:
        # hand-written v1 segment: payload IS the line, no tenant byte
        payload = b"legacy line"
        rec = struct.pack("<II", len(payload),
                          zlib.crc32(payload) & 0xFFFFFFFF) + payload
        with open(os.path.join(td, f"seg-{0:020d}.wal"), "wb") as f:
            f.write(struct.pack("<8sQ", wal_mod.MAGIC, 0) + rec)
        got = [(line, tenant) for _seq, line, tenant in
               wal_mod.WriteAheadLog(td).replay(0)]
        if got != [("legacy line", wal_mod.DEFAULT_TENANT)]:
            findings.append(AuditFinding(
                "tenancy", "wal-v1-compat-drift", "replay",
                "a pre-tenancy (v1) segment must replay under "
                f"DEFAULT_TENANT; got {got!r}",
            ))
    return findings


def audit_distserve(root: str | None = None) -> list[AuditFinding]:
    """Distributed-serve parity: host-labeled prom == JSON gauges.

    Rank 0's ``/metrics`` serves per-host JSON gauge blocks AND
    host-labeled Prometheus families from ONE source
    (``DistServeDriver.host_gauges``); this audit drives a supervisor
    with synthetic host states — one live, one dead, float and negative
    gauges included — through BOTH renderings via the real methods and
    fails on a JSON gauge missing from the labeled text, a value
    disagreement, or a label collision between hosts (ISSUE 17
    satellite).
    """
    import threading

    from ..runtime.distserve import DistServeDriver, _Host

    findings: list[AuditFinding] = []
    drv = DistServeDriver.__new__(DistServeDriver)
    drv._lock = threading.Lock()
    drv.hosts = {}
    h0 = _Host(0, 0)
    h0.gauges = {
        "lines_per_sec": 1234.5, "queue_depth": 17, "drops_total": 0,
    }
    h0.last_wid = 4
    h1 = _Host(1, 0)
    h1.gauges = {
        "lines_per_sec": 0.0, "queue_depth": 0, "drops_total": 3,
    }
    h1.dead = True
    h1.dead_reason = "audit probe"
    h1.degraded = ["wal"]
    drv.hosts = {0: h0, 1: h1}
    # lineage/SLO/build-info plane (ISSUE 19): the real render methods
    # read these — keep in lockstep with DistServeDriver.__init__
    from types import SimpleNamespace

    from ..runtime.metrics import SloBurnEngine, SloPolicy

    drv.cfg = SimpleNamespace(mesh_shape="hybrid")
    drv.dscfg = SimpleNamespace(hosts=2)
    drv.scfg = SimpleNamespace(lineage=True)
    drv.slo = SloBurnEngine(SloPolicy.parse("drop_rate<=0.001"))
    drv.lineage_records_total = 3
    drv.trend_events_total = 1

    js = drv.host_gauges()
    prom = drv.render_labeled_prom()
    if "ra_build_info{" not in prom:
        findings.append(AuditFinding(
            "distserve", "build-info-missing", "ra_build_info",
            "the distributed /metrics prom rendering dropped the "
            "ra_build_info identity gauge",
        ))
    if set(js) != {"0", "1"}:
        findings.append(AuditFinding(
            "distserve", "host-block-drift", ",".join(sorted(js)),
            "host_gauges() must key one block per host rank",
        ))
    for host, gauges in js.items():
        for key, v in gauges.items():
            if isinstance(v, bool):
                v = int(v)
            if not isinstance(v, (int, float)):
                continue
            body = f"{v:g}" if isinstance(v, float) else f"{v}"
            want = f'ra_serve_host_{key}{{host="{host}"}} {body}'
            if want not in prom.splitlines():
                findings.append(AuditFinding(
                    "distserve", "labeled-gauge-drift", f"{host}/{key}",
                    "a per-host JSON gauge is absent from (or disagrees "
                    "with) the host-labeled Prometheus rendering",
                ))
    # the dead/live flags must disagree BETWEEN the two hosts — a label
    # collision (both series under one host value) would make them agree
    if js["0"]["dead"] == js["1"]["dead"] or js["0"]["live"] == js["1"]["live"]:
        findings.append(AuditFinding(
            "distserve", "label-collision", "dead/live",
            "live and dead hosts render identical flags — per-host "
            "blocks are not independent",
        ))

    # failover-gauge parity (ISSUE 18 satellite): the leader-term /
    # lease-age / spool-replay gauges must flow through the REAL
    # metrics_gauges() (one source of truth) and survive the same
    # ra_serve_ prom rendering the /metrics?format=prom path uses —
    # a gauge added to failover_gauges() but dropped from the merge,
    # or renamed on one side, fails here before any dashboard drifts.
    from ..runtime.autoscale import render_prom

    drv._pending = {}
    drv._deg_lock = threading.Lock()
    drv.degraded = {}
    drv._engine = None
    drv._lease = None
    drv.epoch_store = None
    drv._suffix = None
    for attr in (
        "hosts_spawned", "hosts_dead_total", "hosts_retired_total",
        "windows_published", "next_wid", "total_lines", "live_drops",
        "drops_restored", "late_epochs", "late_epoch_lines",
        "degraded_events", "recovered_events",
    ):
        setattr(drv, attr, 0)
    drv.skipped_windows = []
    drv.term = 7
    drv.spool_replayed_total = 41
    drv.replay_windows_total = 5
    drv.replay_lag_windows = 2
    drv.replay_refused_total = 0
    fg = drv.failover_gauges()
    want_keys = {
        "leader_term", "lease_age_sec", "lease_fenced",
        "spool_replayed_total", "replay_windows_total",
        "replay_lag_windows",
    }
    if set(fg) != want_keys:
        findings.append(AuditFinding(
            "distserve", "failover-gauge-drift",
            ",".join(sorted(set(fg) ^ want_keys)),
            "failover_gauges() keys drifted from the documented set "
            "(DESIGN §23) — dashboards and audit_distserve disagree",
        ))
    allg = drv.metrics_gauges()
    prom_all = render_prom(allg, prefix="ra_serve_").splitlines()
    for key, v in fg.items():
        if allg.get(key) != v:
            findings.append(AuditFinding(
                "distserve", "failover-merge-drift", key,
                "a failover gauge is missing from (or disagrees with) "
                "metrics_gauges() — /metrics no longer carries it",
            ))
            continue
        body = f"{v:g}" if isinstance(v, float) else f"{v}"
        if f"ra_serve_{key} {body}" not in prom_all:
            findings.append(AuditFinding(
                "distserve", "failover-prom-drift", key,
                "a failover gauge present in the JSON /metrics block is "
                "absent from the ra_serve_ Prometheus rendering",
            ))
    # lineage + SLO gauges ride the same merged rendering (ISSUE 19)
    for key, want in (
        ("lineage_records_total", 3),
        ("trend_events_total", 1),
        ("slo_objectives", 1),
    ):
        if allg.get(key) != want:
            findings.append(AuditFinding(
                "distserve", "lineage-gauge-drift", key,
                "a lineage/SLO gauge is missing from (or disagrees "
                "with) the distributed metrics_gauges() merge",
            ))
        elif f"ra_serve_{key} {want}" not in prom_all:
            findings.append(AuditFinding(
                "distserve", "lineage-prom-drift", key,
                "a lineage/SLO gauge present in JSON /metrics is absent "
                "from the ra_serve_ Prometheus rendering",
            ))
    return findings


def audit_epochstore(root: str | None = None) -> list[AuditFinding]:
    """Durable epoch store (DESIGN §25): config/flag lockstep, gauge
    prom parity, and the segment-tree == linear-fold identity.

    Drives a REAL store in a tempdir — spills synthetic epochs through
    the production spill/compact path, then checks (a) every ServeConfig
    ``epoch_store*`` field has a matching ``--epoch-store*`` CLI flag on
    the serve-family subcommands, (b) ``EpochStore.gauges()`` keys all
    carry the ``epochstore_`` prefix and survive the ``ra_serve_``
    Prometheus rendering value-for-value (JSON<->prom parity, the same
    law audit_observability pins for the other planes), (c) a range
    query over the tree is bit-identical to the naive linear fold, and
    (d) both ``epochstore.*`` fault sites are registered (ISSUE 20).
    """
    import shutil
    import tempfile

    import numpy as np

    from ..runtime import epochstore
    from ..runtime.autoscale import render_prom
    from ..runtime.faults import SITES
    from ..config import ServeConfig

    findings: list[AuditFinding] = []
    # (a) config <-> CLI flag lockstep
    _subs, flags = _cli_flags()
    flag_names = {f for _sub, f in flags}
    for field in dataclasses.fields(ServeConfig):
        if not field.name.startswith("epoch_store"):
            continue
        flag = "--" + field.name.replace("_", "-").replace(
            "-bytes", "-mb"
        )
        if flag not in flag_names:
            findings.append(AuditFinding(
                "epochstore", "flag-drift", field.name,
                f"ServeConfig.{field.name} has no {flag} CLI flag",
            ))
    # (d) fault-site registration (audit_faults covers arming/tests)
    for site in ("epochstore.spill", "epochstore.compact"):
        if site not in SITES:
            findings.append(AuditFinding(
                "epochstore", "fault-site-missing", site,
                "the epoch-store fault site is not registered",
            ))

    class _Ep:
        def __init__(self, wid):
            rng = np.random.default_rng(wid)
            self.arrays = {
                "counts_lo": rng.integers(
                    0, 2**32, 8, dtype=np.uint32
                ),
                "counts_hi": np.zeros(8, dtype=np.uint32),
                "cms": rng.integers(0, 2**32, (2, 16), dtype=np.uint32),
                "hll": rng.integers(0, 30, (8, 4), dtype=np.uint32),
                "talk_cms": rng.integers(
                    0, 2**32, (2, 16), dtype=np.uint32
                ),
            }
            self.meta = {
                "id": wid, "lines": 100, "parsed": 90, "skipped": 10,
                "chunks": 1, "drops": 0,
                "started_unix": 1.0 + wid, "ended_unix": 2.0 + wid,
            }
            self.tracker_tables = {0: {wid: wid + 1}}
            self.quarantine = {}

    d = tempfile.mkdtemp(prefix="ra-audit-es-")
    try:
        store = epochstore.EpochStore(d, budget_bytes=8 << 20)
        store.bind_base(0)
        for wid in range(11):
            store.spill(_Ep(wid))
        # (c) tree fold == linear fold, bit for bit
        agg, marker = store.range_agg(1, 9)
        ref, _ = store.naive_range_agg(1, 9)
        if marker is not None or agg is None:
            findings.append(AuditFinding(
                "epochstore", "range-refused", str(marker),
                "a fully-stored range was refused",
            ))
        else:
            for k in sorted(ref.arrays):
                if not np.array_equal(agg.arrays[k], ref.arrays[k]):
                    findings.append(AuditFinding(
                        "epochstore", "fold-shape-drift", k,
                        "segment-tree range fold differs from the "
                        "linear fold — the merge laws broke",
                    ))
            if agg.tables != ref.tables or agg.summary != ref.summary:
                findings.append(AuditFinding(
                    "epochstore", "fold-shape-drift", "tables/summary",
                    "tracker tables or accounting differ between the "
                    "tree fold and the linear fold",
                ))
        # (b) gauge naming + JSON <-> prom value parity
        g = store.gauges()
        prom = render_prom(g, prefix="ra_serve_").splitlines()
        for key, v in g.items():
            if not key.startswith("epochstore_"):
                findings.append(AuditFinding(
                    "epochstore", "gauge-prefix-drift", key,
                    "EpochStore.gauges() keys must carry the "
                    "epochstore_ prefix (namespaced /metrics merge)",
                ))
                continue
            body = f"{v:g}" if isinstance(v, float) else f"{v}"
            if f"ra_serve_{key} {body}" not in prom:
                findings.append(AuditFinding(
                    "epochstore", "gauge-prom-drift", key,
                    "a store gauge present in JSON /metrics is absent "
                    "from the ra_serve_ Prometheus rendering",
                ))
        if g.get("epochstore_spilled_total") != 11:
            findings.append(AuditFinding(
                "epochstore", "gauge-count-drift", "spilled_total",
                "the spill counter disagrees with the spills driven",
            ))
        store.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return findings


def audit_registry(root: str | None = None) -> list[AuditFinding]:
    """All eight audits, in declaration order."""
    return (
        audit_faults(root) + audit_cli(root) + audit_volatile(root)
        + audit_retry(root) + audit_observability(root)
        + audit_tenancy(root) + audit_distserve(root)
        + audit_epochstore(root)
    )
