"""Repo-level registry auditor — the drift the jaxprs cannot see.

Three registries pair a declaration site with scattered consumption
sites, and nothing structural kept them in sync until now:

- **fault sites** (``runtime/faults.py::SITES``) <-> armed ``fire()``
  call sites in the package <-> test coverage (a registered site no
  test ever fires is an untested failure mode; a ``fire()`` naming an
  unregistered site can never fire at all);
- **CLI flags** (``cli.make_parser()``) <-> README documentation <->
  PARITY subcommand rows (an undocumented flag is invisible to
  operators; README mentions of flags that no longer exist mislead);
- **VOLATILE totals keys** (``runtime/report.py::VOLATILE_TOTALS`` —
  the keys report-identity tests strip) <-> the runtime code that
  actually produces those totals (a volatile key nothing produces is
  dead weight; a test module keeping its own private list can drift);
- **retry sites** (``runtime/retrypolicy.py::RETRY_SITES``) <-> the
  policy table <-> ``retrypolicy.call()`` call sites <-> chaos
  coverage: every registered seam must have a policy entry, a
  transient chaos schedule (``fault_site@N:k`` with single-digit k —
  below the attempt bound, so the schedule proves RECOVERY), and a
  permanent-escalation test (``fault_site@N:kk`` with k >= 10 — past
  any attempt bound, so the schedule proves the typed escalation).

Pure stdlib + argparse introspection: no device, no jax import beyond
what ``cli`` itself pulls in.
"""

from __future__ import annotations

import dataclasses
import os
import re


@dataclasses.dataclass(frozen=True)
class AuditFinding:
    registry: str  # {"faults", "cli", "volatile"}
    kind: str
    subject: str
    detail: str = ""


def _repo_root(explicit: str | None = None) -> str:
    if explicit:
        return os.path.abspath(explicit)
    # ruleset_analysis_tpu/verify/registry.py -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _read(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            return f.read()
    except OSError:
        return ""


def _py_files(root: str, subdir: str) -> list[str]:
    out = []
    base = os.path.join(root, subdir)
    for dirpath, _dirs, files in os.walk(base):
        for f in files:
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


_FIRE_RE = re.compile(r"""fire\(\s*\n?\s*["']([a-z0-9_.]+)["']""")


def audit_faults(root: str | None = None) -> list[AuditFinding]:
    """SITES <-> armed fire() call sites <-> test coverage."""
    from ..runtime.faults import SITES

    root = _repo_root(root)
    findings: list[AuditFinding] = []
    fired: set[str] = set()
    for path in _py_files(root, "ruleset_analysis_tpu"):
        if path.endswith(os.path.join("runtime", "faults.py")):
            continue
        for m in _FIRE_RE.finditer(_read(path)):
            fired.add(m.group(1))
    tests_text = "".join(_read(p) for p in _py_files(root, "tests"))
    for site in sorted(SITES):
        if site not in fired:
            findings.append(AuditFinding(
                "faults", "registered-never-armed", site,
                "no faults.fire() call site names this registered site",
            ))
        if site not in tests_text:
            findings.append(AuditFinding(
                "faults", "registered-never-tested", site,
                "no test schedules or references this fault site",
            ))
    for site in sorted(fired - set(SITES)):
        findings.append(AuditFinding(
            "faults", "armed-unregistered", site,
            "fire() names a site missing from SITES — it can never fire",
        ))
    return findings


def _cli_flags():
    """(subcommand, long-flag) pairs + subcommand list from the parser."""
    import argparse

    from ..cli import make_parser

    ap = make_parser()
    subs = next(
        a for a in ap._actions if isinstance(a, argparse._SubParsersAction)
    )
    flags = set()
    for name, sp in subs.choices.items():
        for act in sp._actions:
            for o in act.option_strings:
                if o.startswith("--") and o != "--help":
                    flags.add((name, o))
    return sorted(subs.choices), sorted(flags)


def audit_cli(root: str | None = None) -> list[AuditFinding]:
    """CLI flags <-> README; subcommands <-> README + PARITY."""
    root = _repo_root(root)
    findings: list[AuditFinding] = []
    readme = _read(os.path.join(root, "README.md"))
    parity = _read(os.path.join(root, "PARITY.md"))
    subcommands, flags = _cli_flags()
    for name, flag in flags:
        if flag not in readme:
            findings.append(AuditFinding(
                "cli", "flag-undocumented", f"{name} {flag}",
                "flag absent from README.md",
            ))
    for name in subcommands:
        if name not in readme:
            findings.append(AuditFinding(
                "cli", "subcommand-undocumented", name,
                "subcommand absent from README.md",
            ))
        if name not in parity:
            findings.append(AuditFinding(
                "cli", "subcommand-no-parity-row", name,
                "subcommand absent from PARITY.md",
            ))
    return findings


_LOCAL_VOLATILE_RE = re.compile(r"^VOLATILE\s*=\s*\(", re.M)


def audit_volatile(root: str | None = None) -> list[AuditFinding]:
    """VOLATILE_TOTALS <-> totals producers <-> per-test-module drift."""
    from ..runtime.report import VOLATILE_TOTALS

    root = _repo_root(root)
    findings: list[AuditFinding] = []
    runtime_text = "".join(
        _read(p) for p in _py_files(root, "ruleset_analysis_tpu")
    )
    for key in VOLATILE_TOTALS:
        # a volatile key must correspond to a real totals producer
        # somewhere in the runtime (dict literal key or totals[...] set)
        if f'"{key}"' not in runtime_text and f"'{key}'" not in runtime_text:
            findings.append(AuditFinding(
                "volatile", "volatile-key-never-produced", key,
                "VOLATILE_TOTALS names a totals key no runtime code "
                "produces",
            ))
    for path in _py_files(root, "tests"):
        if _LOCAL_VOLATILE_RE.search(_read(path)):
            findings.append(AuditFinding(
                "volatile", "local-volatile-list", os.path.basename(path),
                "test module defines its own VOLATILE tuple instead of "
                "importing runtime.report.VOLATILE_TOTALS — lists drift",
            ))
    return findings


_RETRY_CALL_RE = re.compile(
    r"""retrypolicy\.call\(\s*\n?\s*["']([a-z0-9_.]+)["']"""
)


def audit_retry(root: str | None = None) -> list[AuditFinding]:
    """RETRY_SITES <-> policy table <-> call sites <-> chaos coverage.

    The chaos-coverage convention is positional in the schedule string:
    ``site@N:k`` with a SINGLE-digit k is a transient schedule (k below
    every attempt bound — the harness asserts recovery + bit-identity),
    while k with two or more digits (the suites use ``:99``) is a
    budget-exhaustion schedule (the harness asserts the escalation
    stays typed).  Tests therefore declare their schedules as literal
    strings; this audit greps for them.
    """
    from ..runtime.faults import SITES
    from ..runtime.retrypolicy import DEFAULT_POLICIES, RETRY_SITES

    root = _repo_root(root)
    findings: list[AuditFinding] = []
    called: set[str] = set()
    for path in _py_files(root, "ruleset_analysis_tpu"):
        if path.endswith(os.path.join("runtime", "retrypolicy.py")):
            continue
        for m in _RETRY_CALL_RE.finditer(_read(path)):
            called.add(m.group(1))
    tests_text = "".join(_read(p) for p in _py_files(root, "tests"))
    for site, meta in sorted(RETRY_SITES.items()):
        if site not in DEFAULT_POLICIES:
            findings.append(AuditFinding(
                "retry", "site-without-policy", site,
                "RETRY_SITES entry has no DEFAULT_POLICIES row",
            ))
        if site not in called:
            findings.append(AuditFinding(
                "retry", "registered-never-called", site,
                "no retrypolicy.call() site names this registered seam",
            ))
        if meta.fault_site not in SITES:
            findings.append(AuditFinding(
                "retry", "fault-site-unregistered", site,
                f"maps to fault site {meta.fault_site!r} missing from "
                "faults.SITES",
            ))
        fs = re.escape(meta.fault_site)
        if not re.search(fs + r"@\d+:[1-9](?!\d)", tests_text):
            findings.append(AuditFinding(
                "retry", "no-transient-schedule", site,
                f"no test schedules {meta.fault_site}@N:k (single-digit "
                "k) — the recovery half of the seam is untested",
            ))
        if not re.search(fs + r"@\d+:\d{2,}", tests_text):
            findings.append(AuditFinding(
                "retry", "no-escalation-test", site,
                f"no test schedules {meta.fault_site}@N:kk (k >= 10) — "
                "budget exhaustion escalating typed is untested",
            ))
    for site in sorted(called - set(RETRY_SITES)):
        findings.append(AuditFinding(
            "retry", "called-unregistered", site,
            "retrypolicy.call() names a site missing from RETRY_SITES",
        ))
    return findings


def audit_registry(root: str | None = None) -> list[AuditFinding]:
    """All four audits, in declaration order."""
    return (
        audit_faults(root) + audit_cli(root) + audit_volatile(root)
        + audit_retry(root)
    )
