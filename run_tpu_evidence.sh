#!/bin/bash
# One-shot TPU evidence capture: headline bench + perf suite configs.
# Run with NO env overrides (the default env selects the axon TPU).
# Produces:
#   BENCH_r03_local.json        headline (self-validating, e2e decomposition)
#   BENCH_SUITE_r03_tpu.json    exact/pallas/multifw/e2e + accuracy configs
set -u
cd "$(dirname "$0")"
echo "=== headline bench ===" >&2
timeout 2400 python bench.py > BENCH_r03_local.json 2> /tmp/bench_r03.log
echo "headline rc=$?" >&2
tail -3 /tmp/bench_r03.log >&2
echo "=== suite (perf configs on TPU) ===" >&2
timeout 3600 python bench_suite.py exact pallas multifw recall e2e \
    > /tmp/suite_tpu.jsonl 2> /tmp/suite_tpu.log
echo "suite rc=$?" >&2
{
  echo '{"note": "TPU run (axon tunnel). cms/hll/topk accuracy lines carried from the committed interim artifact (platform-independent).", "platform": "tpu"}'
  cat /tmp/suite_tpu.jsonl
  grep -E '"config2_|"config3_|"config5_' BENCH_SUITE_r03_interim_cpu.json
} > BENCH_SUITE_r03_tpu.json
echo "wrote BENCH_r03_local.json and BENCH_SUITE_r03_tpu.json" >&2
