#!/bin/bash
# One-shot TPU evidence capture: headline bench + perf suite configs.
# Run with NO env overrides (the default env selects the axon TPU).
# Produces:
#   BENCH_r04_local.json        headline (self-validating, e2e decomposition)
#   BENCH_SUITE_r04_tpu.json    exact/pallas/multifw/e2e + accuracy configs
set -u
cd "$(dirname "$0")"
echo "=== headline bench ===" >&2
# no outer timeout: bench.py self-bounds (probe 3x60s + 1800s TPU child +
# 900s CPU fallback) and always emits exactly one JSON line
python bench.py > BENCH_r04_local.json 2> /tmp/bench_r04.log
echo "headline rc=$?" >&2
tail -3 /tmp/bench_r04.log >&2
echo "=== suite (perf configs on TPU) ===" >&2
timeout 5400 python bench_suite.py exact pallas multifw recall e2e stage \
    > /tmp/suite_tpu.jsonl 2> /tmp/suite_tpu.log
suite_rc=$?
echo "suite rc=$suite_rc" >&2
n_lines=$(grep -c '^{' /tmp/suite_tpu.jsonl || true)
{
  echo "{\"note\": \"TPU run (axon tunnel). cms/hll/topk accuracy lines carried from the committed interim artifact (platform-independent).\", \"platform\": \"tpu\", \"suite_rc\": $suite_rc, \"suite_configs_completed\": $n_lines, \"complete\": $([ "$suite_rc" -eq 0 ] && echo true || echo false)}"
  cat /tmp/suite_tpu.jsonl
  grep -E '"config2_|"config3_|"config5_' BENCH_SUITE_r03_interim_cpu.json
} > BENCH_SUITE_r04_tpu.json
if [ "$suite_rc" -ne 0 ]; then
  echo "WARNING: suite incomplete (rc=$suite_rc, $n_lines configs) — artifact is marked partial" >&2
fi
echo "wrote BENCH_r04_local.json and BENCH_SUITE_r04_tpu.json" >&2
